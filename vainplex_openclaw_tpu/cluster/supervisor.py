"""Cluster supervisor: routing, health, lease-fenced failover.

The supervisor owns the membership ring, the lease table, and the **route
log** — every op is published onto the events spine (``cluster.route.<ws>``
subjects over the existing transport machinery) *before* delivery, making
the cross-shard communication schedule an explicit, replayable artifact
(TACCL's argument applied at the process level): per-workspace watermarks
advance only on worker acks, and a failover re-fetches everything past the
watermark for the moved workspaces — redelivery comes from the spine, not
from bespoke in-memory buffers.

Failure detection is layered exactly like the rest of the resilience stack:
a per-worker :class:`CircuitBreaker` absorbs delivery errors, heartbeat
probes run on a miss-limit deadline, and a dead process (``ProcessWorker``)
is its own signal. Failover is the sequence the chaos suite pins:

1. remove the worker from the ring (bounded movement: only its keys move);
2. per moved workspace — ``grant`` a new lease (epoch++, journal-persisted,
   **fence file written durably** before anything else happens);
3. the new owner recovers the workspace by journal replay *before* traffic
   (``add_workspace``), under a RetryPolicy for transient recovery faults;
4. replay the route log past the acked watermark to the new owner.

Stage attribution lands on one StageTimer (``route`` / ``recover`` /
``rebalance``), registered in the gateway quantile registry as ``cluster``
so sitrep and the SLO harness read it like any other edge.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Any, Callable, Optional

from ..events.envelope import ClawEvent
from ..resilience.faults import FaultError, maybe_fail
from ..resilience.policy import CircuitBreaker, RetryPolicy
from ..utils.stage_timer import StageTimer
from .ring import HashRing, LeaseTable
from .worker import InProcessWorker, ProcessWorker, WorkerCrashed

CLUSTER_DEFAULTS = {
    # Escape hatch: nothing builds a cluster unless asked — the default
    # single-process path is byte-for-byte the pre-cluster gateway.
    "enabled": False,
    "workers": 2,
    "vnodes": 160,
    "ackEveryOps": 16,
    "heartbeatMissLimit": 3,
    "heartbeatDeadlineS": 1.5,
    "routeSubject": "cluster.route",
    "deterministicIds": False,
    "recoverRetries": 3,
    # Bounded-load placement cap: no worker owns more than this factor of
    # the mean lease count (consistent hashing with bounded loads). 1.15
    # keeps the max-loaded worker within 15% of fair share — the balance
    # term that dominates measured scaling efficiency.
    "loadFactor": 1.15,
}


class _WorkerState:
    __slots__ = ("handle", "alive", "misses", "breaker", "last_hb",
                 "last_miss_at")

    def __init__(self, handle, breaker: CircuitBreaker, now: float):
        self.handle = handle
        self.alive = True
        self.misses = 0
        self.breaker = breaker
        self.last_hb = now
        self.last_miss_at = 0.0


class ClusterSupervisor:
    """Routes ops to workspace-sharded workers and survives their deaths.

    ``on_result(op, obs)`` fires for every op the cluster finishes —
    including redeliveries after a failover, which OVERWRITE the op's
    earlier (rolled-back) observation when the caller keys by ``op["i"]``;
    that keying is what makes at-least-once delivery read as exactly-once
    accounting.

    State-effect semantics depend on ``journal_cfg``: with the PR-7
    defaults, a commit can land between acks (batch-full / window timer),
    so a crash redelivers a committed-but-unacked tail — at-least-once
    effects, the journal layer's standing contract. Configs that make the
    ack boundary the sole commit trigger (``maxBatchRecords`` huge,
    ``windowMs`` 0 — what the chaos storms pin) tighten that to
    exactly-once state; docs/cluster.md walks the trade."""

    def __init__(self, root: str | Path, config: Optional[dict] = None,
                 clock: Callable[[], float] = time.time,
                 transport=None, logger=None,
                 worker_mode: str = "inproc", wall_timers: bool = True,
                 settable_clock: Any = None, journal_cfg: Any = True,
                 lifecycle_cfg: Any = True,
                 on_result: Optional[Callable[[dict, dict], None]] = None):
        cfg = dict(CLUSTER_DEFAULTS)
        cfg.update(config or {})
        self.cfg = cfg
        self.root = Path(root)
        self.clock = clock
        self.logger = logger
        self.worker_mode = worker_mode
        self.wall_timers = wall_timers
        self.settable_clock = settable_clock
        self.journal_cfg = journal_cfg
        # Workspace lifecycle (ISSUE 11): with the default settings a new
        # owner's recovery loads the last shipped snapshot + wal tail —
        # failover cost tracks the ship cadence, not the journal's age.
        self.lifecycle_cfg = lifecycle_cfg
        self.on_result = on_result or (lambda op, obs: None)
        self.timer = StageTimer()
        self.ring = HashRing(int(cfg.get("vnodes", 160)))
        self.leases = LeaseTable(self.root / "cluster", clock=clock,
                                 logger=logger)
        if transport is None:
            from ..events.transport import MemoryTransport

            transport = MemoryTransport(clock=clock)
        self.transport = transport
        self._route_subject = str(cfg.get("routeSubject", "cluster.route"))
        self._recover_retry = RetryPolicy(
            max_attempts=int(cfg.get("recoverRetries", 3)),
            base_delay_s=0.0, jitter=0.0, sleep=lambda _s: None)
        self._result_q = None
        if worker_mode == "process":
            from .worker import mp_context

            # Queues and processes must come from one context; mp_context
            # picks spawn where possible (fork-with-threads deadlocks the
            # child — see worker.py).
            self._result_q = mp_context().Queue()

        # ── guarded state (self._lock; see the GUARDED table, ISSUE 8) ──
        self._lock = threading.Lock()
        self._workers: dict[str, _WorkerState] = {}
        self._acked: dict[str, int] = {}      # ws -> route-log watermark
        self._inflight: dict[int, str] = {}   # route seq -> ws
        self._backlog: list[tuple[int, dict]] = []
        self._failovers: list[dict] = []
        self.routed = 0
        self.redelivered = 0
        self.route_faults = 0

        for i in range(int(cfg.get("workers", 2))):
            self.add_worker(f"w{i}")

    # ── membership ───────────────────────────────────────────────────

    def _make_handle(self, worker_id: str):
        worker_root = self.root / "workers" / worker_id
        if self.worker_mode == "process":
            return ProcessWorker(worker_id, worker_root, self._result_q,
                                 ack_every=int(self.cfg.get("ackEveryOps", 16)),
                                 journal_cfg=self.journal_cfg,
                                 lifecycle_cfg=self.lifecycle_cfg)
        return InProcessWorker(
            worker_id, worker_root, clock=self.clock,
            ack_every=int(self.cfg.get("ackEveryOps", 16)),
            wall_timers=self.wall_timers,
            deterministic_ids=bool(self.cfg.get("deterministicIds", False)),
            settable_clock=self.settable_clock,
            journal_cfg=self.journal_cfg, lifecycle_cfg=self.lifecycle_cfg,
            logger=self.logger)

    def add_worker(self, worker_id: str) -> None:
        handle = self._make_handle(worker_id)
        breaker = CircuitBreaker(failure_threshold=3, failure_rate=0.5,
                                 window_s=30.0, recovery_s=5.0,
                                 clock=self.clock)
        state = _WorkerState(handle, breaker, self.clock())
        with self._lock:
            self._workers[worker_id] = state
        self.ring.add(worker_id)

    def workers(self) -> dict:
        with self._lock:
            return dict(self._workers)

    def _worker(self, worker_id: str) -> Optional[_WorkerState]:
        with self._lock:
            return self._workers.get(worker_id)

    # ── routing ──────────────────────────────────────────────────────

    def _subject(self, op: dict) -> str:
        return f"{self._route_subject}.{op['wsKey']}"

    def _publish_route(self, op: dict) -> int:
        """Append the op to the route log; returns its spine sequence (the
        redelivery watermark unit). A publish failure (counted by the
        transport) degrades replay coverage for this op, never delivery."""
        event = ClawEvent(
            id=f"route:{op.get('i')}", ts=self.clock() * 1000.0,
            agent="cluster", session="cluster", type="cluster.route",
            canonical_type=None, legacy_type=None, schema_version=1,
            source={"component": "cluster-supervisor"}, actor={}, scope={},
            trace={}, visibility="internal", payload=dict(op))
        if not self.transport.publish(self._subject(op), event):
            return -1
        return self.transport.last_sequence()

    def _placement(self, incoming: int = 1) -> tuple[dict, int]:
        """Current per-live-worker lease counts and the bounded-load cap
        sized for ``incoming`` additional grants. O(leases) — grants are
        rare (first sight, failover), delivery never pays this."""
        import math

        live = set(self.ring.members())
        counts = {w: 0 for w in live}
        for lease in self.leases.snapshot().values():
            if lease["owner"] in counts:
                counts[lease["owner"]] += 1
        total = sum(counts.values())
        cap = max(1, math.ceil(float(self.cfg.get("loadFactor", 1.15))
                               * (total + incoming) / max(1, len(live))))
        return counts, cap

    def _ensure_owner(self, ws: str, ws_key: str) -> str:
        """Current live owner of ``ws``, leasing it on first sight. The
        first grant is a failover-shaped path minus the recovery replay
        (nothing to recover on a fresh workspace — but the fence is written
        either way, so epoch 1 is fenceable from the very first write)."""
        owner = self.leases.owner(ws)
        if owner is not None:
            state = self._worker(owner)
            if state is not None and state.alive:
                return owner
        loads, cap = self._placement()
        new_owner = self.ring.owner(ws_key, loads, cap)
        epoch = self.leases.grant(ws, new_owner)
        state = self._worker(new_owner)
        t0 = time.perf_counter
        start = t0()
        self._recover_retry.call(
            lambda: state.handle.add_workspace(ws, epoch),
            retry_on=(FaultError, OSError))
        self.timer.add("recover", (t0() - start) * 1000.0)
        return new_owner

    def submit(self, op: dict) -> Optional[dict]:
        """Route one op: publish to the route log, deliver to the owner.
        Returns the op's observation when delivery was synchronous (the
        in-process shape); process-mode results arrive via ``tick()``."""
        self._drain_backlog()
        pc = time.perf_counter
        t0 = pc()
        seq = self._publish_route(op)
        try:
            maybe_fail("cluster.route")
        except FaultError:
            with self._lock:
                self.route_faults += 1
                self._backlog.append((seq, op))
                if seq >= 0:
                    self._inflight[seq] = op["ws"]
            self.timer.add("route", (pc() - t0) * 1000.0)
            return None
        obs = self._deliver(seq, op)
        self.timer.add("route", (pc() - t0) * 1000.0)
        return obs

    def _deliver(self, seq: int, op: dict) -> Optional[dict]:
        ws = op["ws"]
        owner = self._ensure_owner(ws, op["wsKey"])
        state = self._worker(owner)
        with self._lock:
            self.routed += 1
            if seq >= 0:
                self._inflight[seq] = ws
        try:
            obs, acked = state.handle.deliver(seq, op)
        except WorkerCrashed as exc:
            state.breaker.record_failure(str(exc))
            self.failover(owner, reason=f"crash during delivery: {exc}")
            return None
        state.breaker.record_success()
        if state.handle.sync:
            self.on_result(op, obs)
            if acked:
                self._note_ack(acked)
        return obs

    def _note_ack(self, seqs: list) -> None:
        with self._lock:
            for seq in seqs:
                ws = self._inflight.pop(seq, None)
                if ws is not None and seq > self._acked.get(ws, 0):
                    self._acked[ws] = seq

    def _drain_backlog(self) -> None:
        with self._lock:
            if not self._backlog:
                return
            backlog, self._backlog = self._backlog, []
        for seq, op in backlog:
            self._deliver(seq, op)

    # ── health / failover ────────────────────────────────────────────

    def tick(self) -> None:
        """One health pass: drain process-mode messages, probe heartbeats,
        fail over anything past its deadline. The deterministic storms call
        this between ops; wall deployments call it on an interval."""
        self._drain_results()
        self._drain_backlog()
        deadline = float(self.cfg.get("heartbeatDeadlineS", 1.5))
        limit = int(self.cfg.get("heartbeatMissLimit", 3))
        with self._lock:
            snapshot = list(self._workers.items())
        for worker_id, state in snapshot:
            if not state.alive:
                continue
            if state.handle.sync:
                try:
                    state.last_hb = state.handle.heartbeat()
                    state.misses = 0
                except WorkerCrashed as exc:
                    self.failover(worker_id, reason=f"crash: {exc}")
                    continue
                except FaultError:
                    state.misses += 1
                    state.breaker.record_failure("heartbeat lost")
            else:
                if not state.handle.alive:
                    self.failover(worker_id, reason="process died")
                    continue
                now = self.clock()
                if now - state.last_hb > deadline:
                    # Rate-limit miss counting to one per deadline window:
                    # tick() may run many times per second (the dispatch
                    # loop calls it), and counting a miss per CALL would
                    # let a burst of quick ticks fail over a worker that is
                    # merely slow to start — missLimit × deadline must be a
                    # WALL-time budget, not a tick budget.
                    if now - max(state.last_hb, state.last_miss_at) > deadline:
                        state.misses += 1
                        state.last_miss_at = now
                        state.breaker.record_failure("heartbeat deadline")
                else:
                    state.misses = 0
            if state.misses >= limit:
                self.failover(worker_id,
                              reason=f"{state.misses} heartbeats missed")

    def _drain_results(self) -> None:
        """Process-mode message pump: results, acks, heartbeats, recovery
        reports — anything from a worker refreshes its liveness stamp."""
        if self._result_q is None:
            return
        import queue as _queue

        while True:
            try:
                msg = self._result_q.get_nowait()
            except _queue.Empty:
                return
            worker_id = msg[1]
            state = self._worker(worker_id)
            if state is not None:
                state.last_hb = time.time()
                state.misses = 0
            kind = msg[0]
            if kind == "res":
                _k, _w, _i, obs, _seq = msg
                self.on_result({"i": _i}, obs)
            elif kind == "ack":
                self._note_ack(msg[2])
            elif kind == "stats" and state is not None:
                # The child's parting gift: final counters + mergeable
                # stage-timer states for the cross-worker quantile view.
                state.handle._final_stats = msg[2]
                state.handle._final_stage_states = msg[3]

    def failover(self, worker_id: str, reason: str = "") -> None:
        """Re-shard a dead worker's workspaces onto the survivors; each
        moved workspace is fenced (epoch++), journal-replay recovered on
        its new owner, then caught up from the route log."""
        pc = time.perf_counter
        t0 = pc()
        with self._lock:
            state = self._workers.get(worker_id)
            if state is None or not state.alive:
                return
            state.alive = False
        if self.logger is not None:
            self.logger.warn(f"[cluster] worker {worker_id} FAILED: {reason}"
                             f" — re-sharding")
        t_reb = pc()
        self.ring.remove(worker_id)
        if not self.ring.members():
            raise RuntimeError("cluster has no live workers left")
        moved = self.leases.owned_by(worker_id)
        loads, cap = self._placement(incoming=len(moved))
        grants: list[tuple[str, str, int]] = []
        for ws in moved:
            new_owner = self.ring.owner(self._ws_key(ws), loads, cap)
            loads[new_owner] = loads.get(new_owner, 0) + 1
            epoch = self.leases.grant(ws, new_owner)
            grants.append((ws, new_owner, epoch))
        self.timer.add("rebalance", (pc() - t_reb) * 1000.0)

        replayed_records = 0
        redelivered = 0
        for ws, new_owner, epoch in grants:
            # Cascading failure: a survivor can die DURING this loop (its
            # crash inside _redeliver triggers a nested failover that
            # re-grants everything it owned — including grants from THIS
            # list). A superseded grant must not be applied: add_workspace
            # at the stale epoch would re-fence the third owner's live
            # journal backwards and drop its buffer.
            if self.leases.epoch(ws) != epoch:
                continue  # re-granted by a nested failover; it owns recovery
            new_state = self._worker(new_owner)
            if new_state is None or not new_state.alive:
                continue  # new owner died; its own failover re-homed the ws
            t_rec = pc()
            replay = self._recover_retry.call(
                lambda: new_state.handle.add_workspace(ws, epoch),
                retry_on=(FaultError, OSError))
            self.timer.add("recover", (pc() - t_rec) * 1000.0)
            replayed_records += (replay or {}).get("records", 0)
            redelivered += self._redeliver(ws, new_state)
        with self._lock:
            self.redelivered += redelivered
            self._failovers.append({
                "at": self.clock(), "worker": worker_id, "reason": reason,
                "workspacesMoved": len(moved),
                "replayedRecords": replayed_records,
                "redelivered": redelivered,
                "durationMs": round((pc() - t0) * 1000.0, 3)})

    def _ws_key(self, ws: str) -> str:
        # The route subject key rides on the op; recover it from the route
        # log's subjects is overkill — tenant keys are the basename by
        # construction in every harness, and a miss only degrades balance,
        # never correctness (the ring accepts any string).
        return Path(ws).name

    def _redeliver(self, ws: str, new_state: _WorkerState) -> int:
        """Replay the route log past the acked watermark — every op whose
        effects the crash rolled back (journal-buffered, never committed,
        never acked) runs again on the new owner, in original order."""
        with self._lock:
            mark = self._acked.get(ws, 0)
        subject = f"{self._route_subject}.{Path(ws).name}"
        count = 0
        for event in self.transport.fetch(subject_filter=subject,
                                          start_seq=mark):
            op = event.payload
            if op.get("ws") != ws:
                continue
            seq = event.seq if event.seq is not None else -1
            try:
                obs, acked = new_state.handle.deliver(seq, op)
            except WorkerCrashed as exc:
                # Cascading failure: the new owner died too. Its own
                # failover (triggered by the next tick/delivery) replays
                # from the same watermarks — nothing is lost, this pass
                # just stops early.
                new_state.breaker.record_failure(str(exc))
                self.failover(new_state.handle.worker_id,
                              reason=f"crash during redelivery: {exc}")
                return count
            count += 1
            if new_state.handle.sync:
                self.on_result(op, obs)
                if acked:
                    self._note_ack(acked)
        return count

    # ── lifecycle / observability ────────────────────────────────────

    def drain(self, timeout_s: float = 30.0) -> None:
        """Deliver anything parked in the route-fault backlog, then flush
        every live worker's ack boundary (and, in process mode, wait for
        the in-flight set to empty). Two backlog→flush rounds: an op a
        route fault parked after the caller's last submit must still be
        delivered AND committed before drain returns — otherwise the
        final op of a run can simply vanish from the accounting."""
        for _ in range(2):
            self._drain_backlog()
            with self._lock:
                snapshot = list(self._workers.values())
            for state in snapshot:
                if not state.alive:
                    continue
                if state.handle.sync:
                    self._note_ack(state.handle.flush())
                else:
                    state.handle.flush()
        if self._result_q is not None:
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                self._drain_results()
                self._drain_backlog()
                with self._lock:
                    if not self._inflight:
                        return
                time.sleep(0.01)

    def stop(self) -> None:
        self.drain()
        with self._lock:
            snapshot = list(self._workers.values())
        if self._result_q is not None:
            # Two-phase shutdown: request every child's exit first, then
            # drain the result queue WHILE waiting — a child's final stats
            # message can exceed the pipe buffer, and an undrained pipe
            # wedges its feeder thread (observed as serial 30s join
            # timeouts per worker on the scaling bench).
            for state in snapshot:
                if state.handle.sync or not state.handle.alive:
                    continue
                try:
                    state.handle.request_stop()
                except Exception:  # noqa: BLE001
                    pass
            deadline = time.time() + 30.0
            while time.time() < deadline:
                self._drain_results()
                if not any((not s.handle.sync) and s.handle.alive
                           for s in snapshot):
                    break
                time.sleep(0.02)
            self._drain_results()
        for state in snapshot:
            try:
                if state.handle.sync:
                    state.handle.stop()
                else:
                    state.handle.finish_stop()
            except Exception as exc:  # noqa: BLE001 — stop paths can't raise
                if self.logger is not None:
                    self.logger.warn(f"[cluster] worker stop failed: {exc}")
        self._drain_results()
        self.leases.close()

    def attach_gateway(self, gw) -> None:
        """Register the cluster's observability on a supervisor-side
        gateway: the ``cluster`` StageTimer edge in the quantile registry
        and the ``cluster.status`` method the sitrep collector reads."""
        gw.stage_timers["cluster"] = self.timer
        gw.methods["cluster.status"] = self.stats

    def stage_snapshots(self, qs=(0.5, 0.95, 0.99)) -> dict:
        """Merged per-edge snapshots across every worker (prefix stripped,
        histograms absorbed bucket-wise) plus the supervisor's own
        ``cluster`` edge — the satellite fix: a multi-worker slo report
        aggregates all workers, not just the supervisor's process."""
        merged: dict[str, StageTimer] = {}
        with self._lock:
            snapshot = list(self._workers.values())
        for state in snapshot:
            prefix = f"{state.handle.worker_id}:"
            for name, st in state.handle.stage_states().items():
                edge = name[len(prefix):] if name.startswith(prefix) else name
                merged.setdefault(edge, StageTimer()).absorb(st)
        out = {edge: timer.snapshot(qs=qs)
               for edge, timer in sorted(merged.items())}
        out["cluster"] = self.timer.snapshot(qs=qs)
        return out

    def stats(self) -> dict:
        with self._lock:
            snapshot = sorted(self._workers.items())
            membership = {"live": [w for w, s in self._workers.items()
                                   if s.alive],
                          "dead": [w for w, s in self._workers.items()
                                   if not s.alive]}
            failovers = list(self._failovers)
            counters = {"routed": self.routed,
                        "redelivered": self.redelivered,
                        "routeFaults": self.route_faults,
                        "inflight": len(self._inflight),
                        "backlog": len(self._backlog)}
        # handle.stats() probes per-workspace journals (path resolution,
        # registry lock) — filesystem-adjacent work that must not run
        # under the hot dispatch lock (GL-LOCK-BLOCKING's rationale, even
        # though the call shape evades the syntactic checker).
        workers = {}
        fenced_total = 0
        for worker_id, state in snapshot:
            row = state.handle.stats()
            row.update({"alive": state.alive,
                        "heartbeatMisses": state.misses,
                        "breaker": state.breaker.stats()})
            fenced_total += row.get("fencedRecords") or 0
            workers[worker_id] = row
        stats = {
            "workers": workers,
            "membership": membership,
            "fencedRecords": fenced_total,
            **counters,
        }
        stats["leases"] = self.leases.snapshot()
        stats["failovers"] = failovers
        stats["lastFailover"] = failovers[-1] if failovers else None
        stats["routeLog"] = {
            "published": self.transport.stats.published,
            "publishFailures": self.transport.stats.publish_failures,
        }
        if self.leases.journal is not None:
            stats["leaseJournal"] = {
                k: self.leases.journal.stats()[k]
                for k in ("commits", "pendingRecords", "lastError")}
        return stats

"""Workspace sharding: consistent-hash ring + epoch-numbered lease table.

The ring answers "who serves this workspace" as a pure function of the
membership set and the key — deterministic across processes, platforms and
insertion orders (sha1, not ``hash()``: ``PYTHONHASHSEED`` must not reshard
the cluster). Virtual nodes give bounded movement: removing a worker moves
ONLY that worker's keys (each to the next point on the ring), adding one
steals ~1/N of the keyspace and touches nobody else's assignments — the
property the rebalance tests pin, because an assignment function that
silently reshuffles unrelated workspaces turns every membership change into
a cluster-wide journal-replay storm.

The :class:`LeaseTable` turns assignments into *ownership*: per workspace an
``(owner, epoch)`` pair where the epoch increments on every grant. Leases
persist through the PR-7 journal (snapshot stream, group-committed), and
each grant stamps the workspace itself with a durable **fence file** — the
single artifact a zombie writer's journal checks at commit time
(:meth:`..storage.journal.Journal.set_fence`). Fencing closes the split-brain
window: a worker the supervisor failed over away from may still be running,
but any write it attempts carries a stale epoch and is rejected at the
journal boundary before it can interleave with the new owner's.
"""

from __future__ import annotations

import hashlib
import threading
from bisect import bisect_right
from pathlib import Path
from typing import Callable, Optional

from ..resilience.faults import maybe_fail
from ..storage.atomic import read_json, write_json_atomic
from ..storage.journal import Journal

FENCE_FILE = "cluster.fence.json"
DEFAULT_VNODES = 160


def _point(label: str) -> int:
    """Stable 64-bit ring coordinate for a label."""
    return int.from_bytes(hashlib.sha1(label.encode("utf-8")).digest()[:8],
                          "big")


class HashRing:
    """Deterministic consistent-hash ring over worker ids.

    Not thread-safe by itself: the supervisor mutates membership under its
    own lock and everyone else only calls the read-only ``owner``/
    ``assignment`` views through it.
    """

    def __init__(self, vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = vnodes
        self._points: list[tuple[int, str]] = []  # sorted (coordinate, worker)
        self._members: set[str] = set()

    def add(self, worker_id: str) -> None:
        if worker_id in self._members:
            return
        self._members.add(worker_id)
        for v in range(self.vnodes):
            self._points.append((_point(f"{worker_id}#{v}"), worker_id))
        self._points.sort()

    def remove(self, worker_id: str) -> None:
        if worker_id not in self._members:
            return
        self._members.discard(worker_id)
        self._points = [p for p in self._points if p[1] != worker_id]

    def members(self) -> list[str]:
        return sorted(self._members)

    def owner(self, key: str, loads: Optional[dict] = None,
              max_load: Optional[int] = None) -> str:
        """The worker whose vnode follows the key's coordinate (wrapping).

        With ``loads``/``max_load`` this is consistent hashing **with
        bounded loads**: successors already at ``max_load`` are skipped, so
        no worker's placement count exceeds the cap (raw vnode hashing
        leaves the max-loaded worker at ~1.3–1.5× mean for realistic key
        counts, which alone caps 4-way scaling near 0.7). Placement stays a
        pure function of ``(members, key, loads, cap)``; the supervisor's
        leases are sticky, so bounded movement is preserved — an existing
        lease is never re-derived, only granted once and moved on failover."""
        if not self._points:
            raise LookupError("ring has no members")
        idx = bisect_right(self._points, (_point(key), "￿"))
        n = len(self._points)
        first = None
        for step in range(n):
            worker = self._points[(idx + step) % n][1]
            if first is None:
                first = worker
            if loads is None or max_load is None \
                    or loads.get(worker, 0) < max_load:
                return worker
        return first  # everyone at cap: fall back to the raw successor

    def assignment(self, keys) -> dict:
        """{key: worker} for a batch of keys (the rebalance diff input)."""
        return {k: self.owner(k) for k in keys}

    def shares(self, keys) -> dict:
        """{worker: fraction of keys} — the balance artifact the scaling
        bench attributes efficiency to (a skewed ring caps the max worker)."""
        keys = list(keys)
        counts: dict[str, int] = {w: 0 for w in self._members}
        for k in keys:
            counts[self.owner(k)] += 1
        total = max(1, len(keys))
        return {w: c / total for w, c in sorted(counts.items())}


class LeaseTable:
    """Per-workspace ``(owner, epoch)`` ownership with journal persistence.

    ``grant`` is the only mutation: it bumps the epoch, journals the full
    table (snapshot stream — coalesced, group-committed, replayed on
    reopen), and stamps the workspace's fence file durably BEFORE returning,
    so by the time a new owner is told to admit traffic every zombie commit
    against that workspace already reads a newer epoch.
    """

    STREAM = "cluster:leases"

    def __init__(self, root: str | Path, clock: Callable[[], float],
                 journal_settings: Optional[dict] = None, logger=None):
        self.root = Path(root)
        self.clock = clock
        self.logger = logger
        self._lock = threading.Lock()
        # Serializes the whole mutate→journal→(rollback|fence) sequence of
        # one grant against other GRANTS only (readers stay on the hot
        # ``_lock``). Without it, two interleaved grants break the abort
        # path both ways: drop_pending() on a failed grant would discard
        # the OTHER grant's buffered payload (its commit then vacuously
        # "succeeds" and stamps a fence for an epoch that never became
        # durable), and the full-table payload snapshotted after a
        # concurrent — later rolled back — mutation would persist the
        # aborted entry. Blocking (group commit + fsync) under this lock
        # is the point: a grant IS a durable control-plane write.
        self._grant_lock = threading.Lock()
        self._leases: dict[str, list] = {}  # ws -> [owner, epoch]
        self.path = self.root / "leases.json"
        try:
            # wall=False always: grant() commits explicitly (lease
            # durability precedes the fence write), so window timers add
            # nothing but a background thread — a fork hazard for the
            # process-worker mode (see worker.mp_context).
            self.journal: Optional[Journal] = Journal(
                self.root / "journal", journal_settings or {}, clock=clock,
                wall=False, logger=logger)
        except OSError:
            self.journal = None  # read-only root: in-memory leases only
        if self.journal is not None:
            self.journal.register_snapshot(self.STREAM, self.path, indent=None)
            # Supervisor restart/adoption (ISSUE 12): grants from a previous
            # supervisor generation are durable in the wal the moment
            # ``grant`` committed, but ``leases.json`` only advances on
            # compaction — fold the replayed records in BEFORE the read, or
            # a replacement supervisor would adopt a stale ownership table.
            self.journal.compact(self.STREAM)
        data = read_json(self.path, None)
        if isinstance(data, dict):
            for ws, lease in (data.get("leases") or {}).items():
                if isinstance(lease, list) and len(lease) == 2:
                    self._leases[str(ws)] = [str(lease[0]), int(lease[1])]

    # ── queries ──────────────────────────────────────────────────────

    def owner(self, ws: str) -> Optional[str]:
        with self._lock:
            lease = self._leases.get(ws)
            return lease[0] if lease else None

    def epoch(self, ws: str) -> int:
        with self._lock:
            lease = self._leases.get(ws)
            return lease[1] if lease else 0

    def owned_by(self, worker_id: str) -> list[str]:
        with self._lock:
            return sorted(ws for ws, (o, _e) in self._leases.items()
                          if o == worker_id)

    def snapshot(self) -> dict:
        with self._lock:
            return {ws: {"owner": o, "epoch": e}
                    for ws, (o, e) in sorted(self._leases.items())}

    # ── the one mutation ─────────────────────────────────────────────

    def grant(self, ws: str, worker_id: str) -> int:
        """Move/establish ownership of ``ws``; returns the new epoch. The
        fence write is the linearization point of the failover — it must
        land before the new owner opens the workspace journal. Grants
        serialize on ``_grant_lock`` (see __init__) so the abort path
        below only ever touches its OWN buffered payload and snapshot."""
        with self._grant_lock:
            with self._lock:
                lease = self._leases.get(ws)
                prior = list(lease) if lease else None
                epoch = (lease[1] if lease else 0) + 1
                self._leases[ws] = [worker_id, epoch]
                payload = {"leases": {w: list(l)
                                      for w, l in sorted(self._leases.items())}}
            if self.journal is not None:
                accepted = self.journal.append(self.STREAM, payload)
                committed = False
                for _attempt in range(3):
                    if self.journal.commit():
                        committed = True
                        break
                if not (accepted and committed):
                    # Lease durability PRECEDES the fence — enforced, not
                    # just stated (ISSUE 13; found by the adoption
                    # crash-point property test): stamping a fence for an
                    # uncommitted grant opens a crash window where a
                    # replacement supervisor folds the wal back to the OLD
                    # epoch while the fence advertises the new one, then
                    # re-issues that epoch — the old and new grantees
                    # would share it and both pass the journal's fence
                    # check. Transient write faults are retried (a torn
                    # wal tail self-repairs on the next commit);
                    # persistent failure aborts the grant UNFENCED — the
                    # same contract as a fence-write fault below. The
                    # abort is complete: the buffered payload is dropped
                    # (left in place, the NEXT successful commit — even
                    # close()'s farewell one — would make the aborted
                    # epoch durable behind the old fence) and the
                    # in-memory entry rolls back to the durable lease
                    # (left advanced, owner() would report the aborted
                    # grantee, so a supervisor that survives the raise
                    # would route traffic to an owner that was never
                    # fenced or recovered). The epoch number is reusable:
                    # it was never durable, never fenced, never returned
                    # to any caller.
                    self.journal.drop_pending()
                    with self._lock:
                        if prior is None:
                            self._leases.pop(ws, None)
                        else:
                            self._leases[ws] = prior
                    raise OSError(self.journal.last_error
                                  or "lease grant commit failed")
            self.write_fence(ws, epoch, worker_id)
        return epoch

    def write_fence(self, ws: str, epoch: int, worker_id: str) -> None:
        """Durable fence stamp inside the workspace itself — the artifact a
        (possibly partitioned) old owner's journal checks at every commit.
        ``cluster.lease`` is a chaos fault site; a failed write raises so
        the supervisor never admits a new owner behind an unwritten fence."""
        maybe_fail("cluster.lease")
        write_json_atomic(Path(ws) / FENCE_FILE,
                          {"epoch": epoch, "owner": worker_id,
                           "grantedAt": self.clock()},
                          indent=None, durable=True)

    @staticmethod
    def read_fence(ws: str | Path) -> Optional[dict]:
        data = read_json(Path(ws) / FENCE_FILE, None)
        return data if isinstance(data, dict) else None

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()

"""Workspace-sharded multi-worker gateway with lease-fenced failover
(ISSUE 9, ROADMAP open item 2).

``cluster.enabled: false`` (the default everywhere) keeps the single-process
gateway path byte-for-byte untouched; this package is pure opt-in scale-out
infrastructure. See docs/cluster.md for the design walkthrough.
"""

from .ring import FENCE_FILE, HashRing, LeaseTable
from .supervisor import (CLUSTER_DEFAULTS, SHEDDABLE_KINDS,
                         ClusterSupervisor, build_route_transport)
from .worker import (InProcessWorker, ProcessWorker, WorkerCrashed,
                     build_worker_gateway, dispatch_op)

__all__ = [
    "CLUSTER_DEFAULTS",
    "ClusterSupervisor",
    "FENCE_FILE",
    "HashRing",
    "InProcessWorker",
    "LeaseTable",
    "ProcessWorker",
    "SHEDDABLE_KINDS",
    "WorkerCrashed",
    "build_route_transport",
    "build_worker_gateway",
    "dispatch_op",
]

"""Workspace-sharded multi-worker gateway with lease-fenced failover
(ISSUE 9, ROADMAP open item 2).

``cluster.enabled: false`` (the default everywhere) keeps the single-process
gateway path byte-for-byte untouched; this package is pure opt-in scale-out
infrastructure. See docs/cluster.md for the design walkthrough.

Fleet serving (ISSUE 17, ``cluster.fleetServing``): model replicas as
cluster residents — ``fleet.ReplicaFleet`` routes stage-3 validator traffic
across worker-owned ContinuousBatchers on the same route-log/failover
machinery workspaces ride, with SLO-driven autoscaling.
"""

from .fleet import FLEET_DEFAULTS, ReplicaFleet, autoscale_decision
from .ring import FENCE_FILE, HashRing, LeaseTable
from .supervisor import (CLUSTER_DEFAULTS, SHEDDABLE_KINDS,
                         ClusterSupervisor, build_route_transport)
from .worker import (InProcessWorker, ProcessWorker, WorkerCrashed,
                     build_worker_gateway, dispatch_op)

__all__ = [
    "CLUSTER_DEFAULTS",
    "ClusterSupervisor",
    "FENCE_FILE",
    "FLEET_DEFAULTS",
    "HashRing",
    "InProcessWorker",
    "LeaseTable",
    "ProcessWorker",
    "ReplicaFleet",
    "SHEDDABLE_KINDS",
    "WorkerCrashed",
    "autoscale_decision",
    "build_route_transport",
    "build_worker_gateway",
    "dispatch_op",
]

"""Cluster workers: the real Gateway, sharded by workspace.

A worker is a full serving stack — governance enforcement + redaction over
its own root, cortex conversation intelligence per tenant workspace — fed
ops by the supervisor and answering with verdict observations. Two shapes
share one contract:

- :class:`InProcessWorker` — the worker pipeline in the supervisor's
  process. This is the deterministic shape: a settable virtual clock, per-op
  id seeding, and seeded fault sites (``cluster.worker.crash``,
  ``cluster.heartbeat``) make a worker-kill storm bit-reproducible, which is
  what lets the chaos suite compare a crashed-and-recovered cluster against
  a never-crashed oracle byte for byte.
- :class:`ProcessWorker` — a real ``multiprocessing.Process`` (stdlib only,
  same discipline as the rest of the repo) speaking over queues: ops in,
  results/acks/heartbeats out. This is the shape the scaling bench runs; a
  ``kill()`` here is a real SIGKILL and failover detection rides
  ``Process.is_alive`` + the heartbeat deadline.

**The ack protocol is the durability boundary.** A worker acks a batch of
route-log sequence numbers only after group-committing every workspace
journal it touched since the previous ack. The supervisor replays
everything past the acked watermark to the new owner after a failover, and
a crash loses only journal-*buffered* (never committed, never acked)
records — so redelivery is effectively exactly-once: the recovered state
contains an op's effects iff that op was acked, and exactly the un-acked
ops are redelivered.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any, Callable, Optional

from ..resilience.faults import FaultError, maybe_fail
from ..storage.journal import peek_journal
from ..utils import ids
from .ring import FENCE_FILE

# One literal per fault site so the package-level registry scan
# (graftlint GL-DRIFT-FAULTSITE) knows the cluster's injection points:
#   cluster.worker.crash — worker dies at a seeded delivery step
#   cluster.heartbeat    — a heartbeat probe is lost (partition)
#   cluster.recover      — workspace recovery on the new owner fails once
#   cluster.route        — transient routing fault in the supervisor
#   cluster.lease        — lease/fence persistence fault (ring.py)
# Planned-handoff stages (ISSUE 12, supervisor.py): drain/barrier/regrant
# faults abort the handoff cleanly pre-grant; resume faults are retried
# post-grant like failover recovery:
#   cluster.handoff.drain / cluster.handoff.barrier /
#   cluster.handoff.regrant / cluster.handoff.resume


class WorkerCrashed(RuntimeError):
    """Raised by a dead worker handle; the supervisor's failover trigger."""


def dispatch_op(gw, kind: str, content: str, ctx: dict) -> dict:
    """Run one workload op through a gateway; returns verdict-path
    observations. Shared by the SLO harness and the cluster workers — one
    implementation, so the single-process and sharded paths can never
    disagree about what an op *is*."""
    if kind == "msg_in":
        gw.message_received(content, ctx)
        return {}
    if kind == "msg_out":
        gw.message_sent(content, ctx)
        return {}
    if kind == "tool_ok" or kind == "tool_denied":
        decision, _ = gw.run_tool("read", {"path": content},
                                  lambda p: f"contents of {content}", ctx)
        return {"blocked": decision.blocked}
    # tool_secret: result must come back redacted (NEVER_SHED path)
    out = gw.tool_result_persist("exec", content, ctx)
    return {"redacted": isinstance(out, str) and "[REDACTED" in out}


def build_worker_gateway(worker_root: str | Path, worker_id: str,
                         clock: Callable[[], float] = time.time,
                         wall_timers: bool = True,
                         journal_cfg: Any = True, lifecycle_cfg: Any = True,
                         logger=None, serve_cfg: Optional[dict] = None):
    """The standard worker profile: governance (credential guard +
    redaction, audit at the worker root) + cortex (per-tenant trackers over
    the shared workspace journals). Stage-timer keys carry the worker's
    prefix so merged cluster views stay attributable."""
    from ..core import Gateway
    from ..cortex import CortexPlugin
    from ..governance import GovernancePlugin

    root = Path(worker_root)
    root.mkdir(parents=True, exist_ok=True)
    config = {"workspace": str(root), "agents": [{"id": worker_id}],
              "cluster": {"workerPrefix": f"{worker_id}:"}}
    kwargs = {} if clock is time.time else {"clock": clock}
    gw = Gateway(config=config, logger=logger, **kwargs)
    gov = GovernancePlugin(workspace=str(root), **kwargs)
    gw.load(gov, plugin_config={
        "redaction": {"enabled": True},
        "builtinPolicies": {"credentialGuard": True,
                            "rateLimiter": {"maxPerMinute": 10_000_000}},
        "storage": {"journal": journal_cfg},
    })
    cortex = CortexPlugin(wall_timers=wall_timers, **kwargs)
    gw.load(cortex, plugin_config={"languages": "all",
                                   "traceAnalyzer": {"enabled": False},
                                   "registerTools": False,
                                   # lifecycle (ISSUE 11): shipping keeps
                                   # per-tenant recovery O(wal tail) after a
                                   # worker death; hibernation bounds a
                                   # worker's resident tenant trackers.
                                   "storage": {"journal": journal_cfg,
                                               "lifecycle": lifecycle_cfg}})
    gw.start()
    if serve_cfg is not None:
        # Fleet serving (ISSUE 17): this worker OWNS a replica batcher out
        # of the PR-15 scoped registry — scope keyed to the worker id so
        # stop()/retirement closes exactly its own collector threads, never
        # a peer's. Built only when a checkpoint is actually servable: the
        # gateway must stay constructible on model-less CI workers (the
        # fleet's injected-factory seam covers those).
        from ..config.loader import deep_merge
        from ..models.pretrained import available
        from ..models.serve import SERVE_DEFAULTS, shared_batcher

        merged = deep_merge(SERVE_DEFAULTS, serve_cfg)
        ckpt = merged.pop("checkpointDir", None)
        if available(ckpt):
            gw.serve_batcher = shared_batcher(
                ckpt, merged, scope=f"{worker_id}@{root}")
        elif logger is not None:
            logger.warn(f"[cluster] worker {worker_id}: serve_cfg given but "
                        "no servable checkpoint; replica batcher skipped")
    return gw, cortex, gov


class InProcessWorker:
    """Deterministic in-process worker (chaos storms, slo --workers)."""

    sync = True

    def __init__(self, worker_id: str, root: str | Path,
                 clock: Callable[[], float] = time.time,
                 ack_every: int = 16, wall_timers: bool = True,
                 deterministic_ids: bool = False,
                 settable_clock: Any = None,
                 journal_cfg: Any = True, lifecycle_cfg: Any = True,
                 logger=None, gateway_builder: Optional[Callable] = None,
                 serve_cfg: Optional[dict] = None):
        self.worker_id = worker_id
        self.root = Path(root)
        # Registry scope for any serve batchers this worker's gateway owns
        # (ISSUE 17): stop() closes exactly this scope — drain first, so
        # planned retirement strands nothing; crash() deliberately leaves
        # it (a corpse's queue is redelivery's job, not teardown's).
        self.serve_scope = f"{worker_id}@{self.root}"
        self.clock = clock
        self.ack_every = max(1, int(ack_every))
        self.deterministic_ids = deterministic_ids
        self._settable_clock = settable_clock
        self.shard: dict[str, int] = {}  # ws -> lease epoch
        self.alive = True
        self.delivered = 0
        self.acked = 0
        self._since_ack: list[int] = []   # route-log seqs awaiting ack
        self._touched: set[str] = set()   # workspaces dirty since last ack
        # Committed-and-acked seqs whose REPORT was lost to a failed
        # release barrier (the commit landed; the OSError preempted the
        # return) — they ride out with the next successful ack, or the
        # supervisor's _inflight entries for them would leak forever.
        self._unreported_acks: list[int] = []
        # gateway_builder is the protocol/payload seam (ISSUE 13): every
        # protocol-bearing method on this class (deliver/ack/fence/crash/
        # release) runs verbatim over whatever stack the builder returns —
        # protolint's interleaving explorer substitutes a stub executor
        # here so exhaustive schedule enumeration doesn't pay a full
        # governance+cortex build per schedule.
        builder_kwargs = dict(
            clock=clock, wall_timers=wall_timers, journal_cfg=journal_cfg,
            lifecycle_cfg=lifecycle_cfg, logger=logger)
        if serve_cfg is not None and gateway_builder is None:
            # Only the default builder understands serve_cfg — injected
            # builders (protolint's stub executor) keep their signature.
            builder_kwargs["serve_cfg"] = serve_cfg
        self.gw, self.cortex, self.gov = (gateway_builder
                                          or build_worker_gateway)(
            self.root, worker_id, **builder_kwargs)

    # ── shard management ─────────────────────────────────────────────

    def add_workspace(self, ws: str, epoch: int) -> dict:
        """Own ``ws`` at lease ``epoch``: recover state by journal replay
        (tracker construction opens the workspace journal, which replays
        wal segments and completes crashed compactions BEFORE the tracker's
        load — the PR-7 contract), then arm the fence so this worker's own
        writes die cleanly if the lease ever moves on. Traffic for ``ws``
        must not be delivered before this returns."""
        maybe_fail("cluster.recover")
        # Takeover barrier: if a previous owner's journal instance is still
        # open in this process (partition-style failover — the worker was
        # presumed dead, not actually dead), adopt it at the new epoch,
        # DISCARD its un-acked buffer (the supervisor redelivers those ops
        # — committing them here would double-apply), and compact the
        # committed records so the files the trackers load reflect exactly
        # the acked prefix. A genuinely crashed owner's journal is
        # abandoned/closed instead, and the fresh open below replays its
        # wal — same end state, two routes.
        stale = peek_journal(ws)
        if stale is not None:
            try:
                stale.set_fence(Path(ws) / FENCE_FILE, epoch)
                stale.drop_pending()
                stale.compact()
            except OSError:
                pass  # failed compaction: recovery replay covers it
        trackers = self.cortex.trackers({"workspace": ws})
        journal = trackers.journal
        replay = {}
        if journal is not None:
            replay = dict(journal.stats()["replay"])
            journal.set_fence(Path(ws) / FENCE_FILE, epoch)
        self.shard[ws] = epoch
        return replay

    def drop_workspace(self, ws: str) -> None:
        self.shard.pop(ws, None)

    def release_workspace(self, ws: str) -> list:
        """Planned-handoff barrier, worker side (ISSUE 12): group-commit
        everything buffered (the ack boundary), then evict the workspace
        through the hibernation seam — flush, durable snapshot ship,
        journal close, tracker cache drop — so the legacy files ARE the
        state, the live wal is rotated empty (the target opens with **zero
        replay**), and this worker retains no stale tracker state to flush
        over the new owner's later. Raises on a failed ship so the
        supervisor aborts the handoff and this worker keeps serving."""
        acked = self._ack()
        try:
            if not self.cortex.release_workspace(ws):
                raise OSError("handoff barrier: release/ship failed")
            journal = peek_journal(ws)
            if journal is not None:
                # Non-cortex streams (audit, events) on a still-open
                # journal: ship them too so nothing is left to replay.
                ok = (journal.ship_snapshot()
                      if journal.lifecycle is not None else journal.compact())
                if not ok:
                    raise OSError(journal.last_error
                                  or "handoff barrier: snapshot ship failed")
        except OSError:
            # The group commit above already landed and cleared
            # _since_ack; losing these seqs with the raise would leak the
            # supervisor's _inflight entries (drains would time out
            # forever). Park them for the next successful ack instead.
            self._unreported_acks.extend(acked)
            raise
        self.shard.pop(ws, None)
        return acked

    # ── delivery / ack ───────────────────────────────────────────────

    def deliver(self, seq: int, op: dict) -> tuple[dict, Optional[list]]:
        """Process one op; returns ``(obs, acked_seqs_or_None)``. The crash
        fault site fires at delivery entry — between ops, where a real
        kill -9 would land — and converts this handle into a corpse: state
        buffered since the last ack is gone (journals abandoned, exactly as
        an OS would drop a dead process's memory)."""
        try:
            maybe_fail("cluster.worker.crash")
        except FaultError as exc:
            self.crash()
            raise WorkerCrashed(str(exc)) from exc
        if not self.alive:
            raise WorkerCrashed(f"{self.worker_id} is dead")
        if self._settable_clock is not None and "at" in op:
            self._settable_clock.t = op["at"]
        if self.deterministic_ids and "ids" in op:
            ids._ID_RNG.seed(op["ids"])
        ws = op["ws"]
        ctx = {"workspace": ws, "agent_id": self.worker_id,
               "session_key": f"agent:{self.worker_id}:cluster"}
        self._ensure_workspace_awake(ws)
        obs = dispatch_op(self.gw, op["kind"], op["content"], ctx)
        self.delivered += 1
        self._touched.add(ws)
        self._since_ack.append(seq)
        if len(self._since_ack) >= self.ack_every:
            return obs, self._ack()
        return obs, None

    def _ensure_workspace_awake(self, ws: str) -> None:
        """Close the hibernation/fencing gap (ISSUE 11): LRU eviction
        closes a tenant's journal, and the wake on the next op opens a
        FRESH instance that knows nothing about the lease — a partitioned
        zombie worker waking a moved tenant would otherwise write unfenced.
        Before dispatching, any sharded workspace whose journal is missing
        or fence-less is woken through the cortex path and re-armed at this
        worker's lease epoch, so the commit-time fence check covers
        post-wake writes exactly like post-takeover ones."""
        epoch = self.shard.get(ws)
        if epoch is None:
            return
        journal = peek_journal(ws)
        if journal is not None and journal.fence_epoch is not None:
            return
        try:
            trackers = self.cortex.trackers({"workspace": ws})
        except OSError:
            return  # wake fault: the dispatch hooks retry fail-open
        if trackers.journal is not None:
            trackers.journal.set_fence(Path(ws) / FENCE_FILE, epoch)

    def _ack(self) -> list:
        """Group-commit every touched journal, then release the seqs. The
        commit is what makes the ack honest: an acked op's effects are on
        disk (per the fsync policy), so failover never needs to replay it.
        A failed commit (transient write fault — retained and retried — or
        a fenced/closed journal) therefore acks NOTHING: releasing seqs
        whose records were dropped would advance the supervisor's watermark
        past ops that never became durable, turning redelivery into loss."""
        ok = True
        for ws in sorted(self._touched):
            journal = peek_journal(ws)
            if journal is not None:
                ok = journal.commit() and ok
        root_journal = peek_journal(self.root)
        if root_journal is not None:
            ok = root_journal.commit() and ok  # worker-own audit/events
        if not ok:
            return []  # seqs + touched set retained; next boundary retries
        self._touched.clear()
        fresh, self._since_ack = self._since_ack, []
        self.acked += len(fresh)
        acked = self._unreported_acks + fresh
        self._unreported_acks = []
        return acked

    def flush(self) -> list:
        return self._ack()

    # ── liveness ─────────────────────────────────────────────────────

    def heartbeat(self) -> float:
        maybe_fail("cluster.heartbeat")
        if not self.alive:
            raise WorkerCrashed(f"{self.worker_id} is dead")
        return self.clock()

    def crash(self) -> None:
        """Die like a process: abandon every journal (buffered records drop,
        committed wal stays for the next owner's replay), keep the gateway
        object only as a corpse. Nothing is flushed, stopped, or compacted."""
        if not self.alive:
            return
        self.alive = False
        for ws in list(self.shard) + [str(self.root)]:
            journal = peek_journal(ws)
            if journal is not None:
                journal.abandon()
        self._since_ack = []
        self._touched.clear()

    kill = crash

    def stop(self) -> None:
        if not self.alive:
            return
        self._ack()
        self.gw.stop()
        # Scoped batcher teardown (ISSUE 17): drain + close ONLY this
        # worker's registry batchers. Before this, close_batchers was
        # process-global atexit — a retired worker stranded its queued
        # serve requests and leaked its collector threads until exit.
        from ..models.serve import close_batchers

        close_batchers(scope=self.serve_scope, drain=True)
        self.alive = False

    # ── observability ────────────────────────────────────────────────

    def stage_states(self) -> dict:
        """Raw mergeable StageTimer states, keyed with the worker prefix."""
        return {name: timer.state()
                for name, timer in self.gw.stage_timers.items()}

    def stats(self) -> dict:
        fenced = 0
        for ws in self.shard:
            journal = peek_journal(ws)
            if journal is not None:
                fenced += journal.fence_rejected
        out = {"workerId": self.worker_id, "alive": self.alive,
               "kind": "inproc", "workspaces": len(self.shard),
               "delivered": self.delivered, "acked": self.acked,
               "unacked": len(self._since_ack),
               "fencedRecords": fenced}
        lc = self.cortex.lifecycle
        if lc is not None:
            out["lifecycle"] = {"wakes": lc.wakes, "evictions": lc.evictions,
                                "hibernateFailures": lc.hibernate_failures}
        return out


# ── real-process worker (the scaling bench shape) ────────────────────


def mp_context():
    """The safest usable multiprocessing context. Prefer ``spawn``: the
    supervisor process carries threads (journal timers, queue feeders,
    logging) and a ``fork`` taken while one of them holds a lock deadlocks
    the child — observed intermittently on this very bench. Spawn requires
    a re-importable ``__main__`` (it re-runs the main module in the child
    under the ``__mp_main__`` guard); interactive/stdin mains don't have
    one, so those fall back to fork, which is safe there exactly because a
    fresh interactive interpreter hasn't started the thread zoo yet."""
    import multiprocessing as mp
    import sys

    main = sys.modules.get("__main__")
    main_file = getattr(main, "__file__", None)
    if main_file and os.path.exists(main_file):
        return mp.get_context("spawn")
    return mp.get_context("fork")


def _process_worker_main(worker_id: str, root: str, ack_every: int,
                         hb_interval_s: float, journal_cfg, lifecycle_cfg,
                         in_q, out_q) -> None:
    """Child entry point: build the worker profile, loop on the op queue.
    Every outbound message doubles as a heartbeat (the supervisor stamps
    ``last_hb`` on anything it drains); an idle child beats explicitly."""
    import queue as _queue

    worker = InProcessWorker(worker_id, root, ack_every=ack_every,
                             wall_timers=True, journal_cfg=journal_cfg,
                             lifecycle_cfg=lifecycle_cfg)
    out_q.put(("hb", worker_id, time.time()))
    while True:
        try:
            msg = in_q.get(timeout=hb_interval_s)
        except _queue.Empty:
            out_q.put(("hb", worker_id, time.time()))
            continue
        kind = msg[0]
        if kind == "ws":
            _k, ws, epoch = msg
            try:
                replay = worker.add_workspace(ws, epoch)
                out_q.put(("recovered", worker_id, ws, replay))
            except OSError as exc:
                out_q.put(("recover_failed", worker_id, ws, str(exc)))
        elif kind == "op":
            _k, seq, op = msg
            try:
                obs, acked = worker.deliver(seq, op)
            except WorkerCrashed:
                break
            out_q.put(("res", worker_id, op.get("i"), obs, seq))
            if acked:
                out_q.put(("ack", worker_id, acked))
        elif kind == "flush":
            out_q.put(("ack", worker_id, worker.flush()))
        elif kind == "release":
            _k, ws = msg
            try:
                acked = worker.release_workspace(ws)
                out_q.put(("ack", worker_id, acked))
                out_q.put(("released", worker_id, ws, True))
            except OSError as exc:
                out_q.put(("released", worker_id, ws, False))
                out_q.put(("release_failed", worker_id, ws, str(exc)))
        elif kind == "stop":
            acked = worker.flush()
            out_q.put(("ack", worker_id, acked))
            out_q.put(("stats", worker_id, worker.stats(),
                       worker.stage_states()))
            worker.stop()
            break


class ProcessWorker:
    """Worker in its own OS process; the contract of :class:`InProcessWorker`
    flipped asynchronous: ``deliver`` enqueues, results/acks/heartbeats
    arrive on the supervisor's shared result queue."""

    sync = False

    def __init__(self, worker_id: str, root: str | Path, out_q,
                 ack_every: int = 16, hb_interval_s: float = 0.25,
                 journal_cfg: Any = True, lifecycle_cfg: Any = True):
        # The worker module imports in ~0.3s with no jax, so spawn's
        # re-import cost (see mp_context) is noise next to gateway build.
        ctx = mp_context()
        self.worker_id = worker_id
        self.root = Path(root)
        self._in_q = ctx.Queue()
        self._out_q = out_q
        self.proc = ctx.Process(
            target=_process_worker_main,
            args=(worker_id, str(root), ack_every, hb_interval_s,
                  journal_cfg, lifecycle_cfg, self._in_q, out_q),
            daemon=True, name=f"cluster-{worker_id}")
        self.proc.start()
        self.shard: dict[str, int] = {}
        self.delivered = 0

    @property
    def alive(self) -> bool:
        return self.proc.is_alive()

    def add_workspace(self, ws: str, epoch: int) -> dict:
        self.shard[ws] = epoch
        self._in_q.put(("ws", ws, epoch))
        return {}

    def drop_workspace(self, ws: str) -> None:
        self.shard.pop(ws, None)

    def release_workspace(self, ws: str) -> list:
        """Asynchronous shape of the handoff barrier: enqueue the release;
        the child acks + ships and answers with a ``released`` message the
        supervisor's result pump records in ``self.released``."""
        if not self.proc.is_alive():
            raise WorkerCrashed(f"{self.worker_id} process is dead")
        # A confirmation from an earlier, timed-out-and-aborted handoff of
        # this workspace may still be parked here; consuming it for THIS
        # release would regrant before the child ran the barrier.
        self.released.pop(ws, None)
        self.shard.pop(ws, None)
        self._in_q.put(("release", ws))
        return []

    # ws -> bool, filled by the supervisor when it drains ("released", …)
    # messages; the handoff barrier polls it (single-reader: the
    # supervisor's dispatch thread, so no lock needed).
    @property
    def released(self) -> dict:
        out = getattr(self, "_released", None)
        if out is None:
            out = self._released = {}
        return out

    def deliver(self, seq: int, op: dict) -> tuple[Optional[dict], None]:
        if not self.proc.is_alive():
            raise WorkerCrashed(f"{self.worker_id} process is dead")
        self._in_q.put(("op", seq, op))
        self.delivered += 1
        return None, None  # results arrive on the shared queue

    def flush(self) -> list:
        self._in_q.put(("flush",))
        return []

    def heartbeat(self) -> float:
        """Liveness only — real heartbeats arrive on the result queue; a
        dead process is the immediate signal."""
        if not self.proc.is_alive():
            raise WorkerCrashed(f"{self.worker_id} process is dead")
        return time.time()

    def kill(self) -> None:
        """Real SIGKILL — the bench's failover clock starts here."""
        if self.proc.is_alive():
            os.kill(self.proc.pid, 9)
        self.proc.join(timeout=5.0)

    def request_stop(self) -> None:
        """Phase one of shutdown: ask the child to flush and exit. The
        caller must keep draining the shared result queue until the child
        exits — its final stats message can be larger than the pipe buffer,
        and an undrained pipe wedges the child's queue feeder thread,
        turning a clean exit into a join timeout."""
        if self.proc.is_alive():
            self._in_q.put(("stop",))

    def finish_stop(self, timeout_s: float = 10.0) -> None:
        self.proc.join(timeout=timeout_s)
        if self.proc.is_alive():
            self.proc.terminate()
            self.proc.join(timeout=5.0)

    def stop(self) -> None:
        self.request_stop()
        self.finish_stop(timeout_s=30.0)

    def stage_states(self) -> dict:
        # Shipped via the ("stats", …) message at stop; the supervisor
        # stores it here when it drains the message.
        return getattr(self, "_final_stage_states", {})

    def stats(self) -> dict:
        return {"workerId": self.worker_id, "alive": self.alive,
                "kind": "process", "workspaces": len(self.shard),
                "delivered": self.delivered, "acked": None,
                "unacked": None, "fencedRecords": None}

"""Plugin kernel: the hook/service/command contract every subsystem plugs into."""

from .api import (
    HookBus,
    HookHandler,
    PluginApi,
    PluginCommand,
    PluginLogger,
    PluginService,
    list_logger,
    make_logger,
)
from .gateway import Gateway, ToolCallDecision, MessageWriteDecision

__all__ = [
    "Gateway",
    "HookBus",
    "HookHandler",
    "MessageWriteDecision",
    "PluginApi",
    "PluginCommand",
    "PluginLogger",
    "PluginService",
    "ToolCallDecision",
    "list_logger",
    "make_logger",
]

"""The plugin API contract.

This is the one seam every subsystem attaches through, equivalent to the
reference's ``OpenClawPluginApi`` (openclaw-governance/src/types.ts:10-41,
duplicated per package there; shared here because the gateway is in-repo).

Semantics:

- Hooks are named lifecycle events (``before_tool_call``, ``message_received``,
  ...). Handlers register with an integer priority and run in **ascending**
  priority order (5 before 950 before 1000), stable by registration order
  within a priority. This matches the reference's observed ordering: redaction
  vault resolution (prio 950) runs before governance enforcement (prio 1000)
  on ``before_tool_call`` (governance/src/redaction/hooks.ts:121-125 vs
  src/hooks.ts:883), and context injection registers at prio 5 to run first.
- Handlers may be sync functions or ``async def``. Certain hooks are declared
  synchronous (``before_message_write`` — reference engine.ts:360-365 requires
  output validation to stay sync) and the bus rejects coroutine results there.
- Every handler invocation is wrapped in try/except: a plugin must never crash
  the gateway (reference: each handler try/caught, e.g. cortex hooks.ts:127-130).
  Errors are logged and counted; the hook continues with later handlers.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional, Protocol, Union

from ..resilience.policy import CircuitBreaker

HookHandler = Callable[..., Union[Any, Awaitable[Any]]]

# Per-plugin error budget defaults (ISSUE 4): a plugin failing ≥90% of its
# last-minute handler calls, with at least 25 failures, is *degraded* — its
# handlers are skipped (visibly: counters + one log line per transition)
# until a recovery probe succeeds. Generous on purpose: the budget exists to
# shed a plugin that is broken, not one that is merely unlucky.
PLUGIN_BREAKER_DEFAULTS = {
    "failureThreshold": 25,
    "failureRate": 0.9,
    "windowS": 60.0,
    "recoveryS": 30.0,
    "halfOpenMax": 1,
}

# Hooks whose handlers may carry a verdict or scrub content before it leaves
# the process (enforcement deny, response gate, redaction of tool results).
# Shedding one of these FAILS OPEN — a denied tool call would silently run,
# a secret would persist unredacted — so the error budget never sheds them:
# their failures still count (the plugin shows degraded in status, and its
# handlers on other hooks shed), but these handlers always run.
NEVER_SHED_HOOKS = frozenset({
    "before_tool_call",
    "message_sending",
    "before_message_write",
    "tool_result_persist",
})

# Hooks whose handlers must be synchronous (results are needed inline, before
# the gateway writes the message).
SYNC_ONLY_HOOKS = frozenset({"before_message_write", "tool_result_persist"})

# Traffic-proportional hooks the admission controller may shed under
# saturation (ISSUE 6). Shedding is HANDLER-granular, not hook-granular:
# a shed fire skips only handlers registered without ``never_shed`` —
# observability/memory work (cortex ingest, knowledge extraction, event
# mirroring) — while verdict-relevant handlers that happen to live on
# these hooks (governance's 2FA code interception on message_received,
# trust feedback + sub-agent linking on after_tool_call) register with
# ``never_shed=True`` and run at any queue depth. Lifecycle hooks
# (session/gateway/compaction boundaries) carry state transitions and are
# not listed at all: shedding is strictly for per-message/per-call volume.
ADMISSION_SHEDDABLE_HOOKS = frozenset({
    "message_received",
    "message_sent",
    "after_tool_call",
    "llm_input",
    "llm_output",
})

KNOWN_HOOKS = (
    "before_tool_call",
    "after_tool_call",
    "tool_result_persist",
    "message_received",
    "message_sending",
    "message_sent",
    "before_message_write",
    "before_agent_start",
    "agent_end",
    "session_start",
    "session_end",
    "before_compaction",
    "after_compaction",
    "gateway_start",
    "gateway_stop",
    "llm_input",
    "llm_output",
)


class SyncDispatchInAsyncContext(RuntimeError):
    """Raised (never swallowed) when a sync fire meets an awaitable while an
    event loop is already running — the caller must use the async entry point;
    silently dropping an enforcement verdict here would fail open."""


class PluginLogger(Protocol):
    def info(self, msg: str) -> None: ...
    def warn(self, msg: str) -> None: ...
    def error(self, msg: str) -> None: ...
    def debug(self, msg: str) -> None: ...


@dataclass
class _StdLogger:
    """Default logger: ``[plugin-id]``-prefixed lines into :mod:`logging`."""

    prefix: str
    _log: logging.Logger = field(default_factory=lambda: logging.getLogger("openclaw"))

    def _fmt(self, msg: str) -> str:
        return msg if msg.startswith("[") else f"[{self.prefix}] {msg}"

    def info(self, msg: str) -> None:
        self._log.info(self._fmt(msg))

    def warn(self, msg: str) -> None:
        self._log.warning(self._fmt(msg))

    def error(self, msg: str) -> None:
        self._log.error(self._fmt(msg))

    def debug(self, msg: str) -> None:
        self._log.debug(self._fmt(msg))


def make_logger(plugin_id: str) -> PluginLogger:
    return _StdLogger(plugin_id)


@dataclass
class ListLogger:
    """Test logger capturing ``(level, msg)`` pairs.

    Mirrors the reference's ``createMockLogger`` fixture
    (cortex/test/trace-analyzer/helpers.ts:149-158) — here it is part of the
    framework because the host harness is first-class.
    """

    records: list[tuple[str, str]] = field(default_factory=list)

    def info(self, msg: str) -> None:
        self.records.append(("info", msg))

    def warn(self, msg: str) -> None:
        self.records.append(("warn", msg))

    def error(self, msg: str) -> None:
        self.records.append(("error", msg))

    def debug(self, msg: str) -> None:
        self.records.append(("debug", msg))

    def messages(self, level: Optional[str] = None) -> list[str]:
        return [m for lv, m in self.records if level is None or lv == level]


def list_logger() -> ListLogger:
    return ListLogger()


@dataclass
class PluginService:
    id: str
    start: Callable[[Any], Any]
    stop: Optional[Callable[[Any], Any]] = None


@dataclass
class PluginCommand:
    name: str
    description: str
    handler: Callable[..., dict]
    require_auth: bool = False
    accepts_args: bool = False


@dataclass
class _Registration:
    priority: int
    seq: int
    plugin_id: str
    handler: HookHandler
    is_async: bool = False
    # Exempt from admission shedding (ISSUE 6): verdict-relevant work
    # registered on an otherwise-sheddable hook.
    never_shed: bool = False


@dataclass
class HookStats:
    fired: int = 0
    errors: int = 0
    # Handlers skipped without running: plugin error-budget breaker open,
    # OR admission-control shed (ISSUE 6) — both deliberate, both visible.
    skipped: int = 0
    last_fired_at: Optional[float] = None
    last_error: Optional[str] = None


class HookBus:
    """Priority-ordered hook dispatch with per-hook fire/error diagnostics.

    Diagnostics mirror cortex's per-hook fire counters
    (cortex/src/hooks.ts:31-36,71-77) but live in the kernel so every plugin
    gets them for free.
    """

    def __init__(self, logger: Optional[PluginLogger] = None, clock: Callable[[], float] = time.time,
                 breaker_config: Optional[dict] = None):
        self._handlers: dict[str, list[_Registration]] = {}
        self._snapshots: dict[str, list[_Registration]] = {}
        self._async_memo: dict[str, bool] = {}
        self._seq = 0
        self._logger = logger or make_logger("hook-bus")
        self._clock = clock
        self.stats: dict[str, HookStats] = {}
        # Per-(plugin, hook) error-budget breakers — per hook, not per
        # plugin, so one broken handler can't be masked by the same plugin's
        # healthy traffic on OTHER hooks (and a never-shed hook's successes
        # can't close a half-open breaker that a sheddable hook tripped).
        # ``breaker_config`` merges over PLUGIN_BREAKER_DEFAULTS;
        # {"enabled": False} disables shedding entirely (errors are still
        # logged and counted, seed behavior).
        cfg = dict(PLUGIN_BREAKER_DEFAULTS)
        cfg.update(breaker_config or {})
        self._breaker_cfg = cfg if cfg.get("enabled", True) else None
        self.breakers: dict[tuple[str, str], CircuitBreaker] = {}

    def _breaker_for(self, plugin_id: str, hook_name: str) -> Optional[CircuitBreaker]:
        cfg = self._breaker_cfg
        if cfg is None:
            return None
        key = (plugin_id, hook_name)
        br = self.breakers.get(key)
        if br is None:
            br = self.breakers[key] = CircuitBreaker(
                failure_threshold=int(cfg["failureThreshold"]),
                failure_rate=float(cfg["failureRate"]),
                window_s=float(cfg["windowS"]),
                recovery_s=float(cfg["recoveryS"]),
                half_open_max=int(cfg["halfOpenMax"]),
                clock=self._clock)
        return br

    def _record_handler_failure(self, br: Optional[CircuitBreaker],
                                plugin_id: str, hook_name: str, err: str) -> None:
        if br is None:
            return
        was_open = br.state == "open"
        br.record_failure(err)
        if not was_open and br.state == "open":
            shed = ("handlers shed" if hook_name not in NEVER_SHED_HOOKS
                    else "never shed (verdict-bearing hook), failures visible")
            self._logger.error(
                f"[hook-bus] plugin '{plugin_id}' DEGRADED on '{hook_name}': "
                f"error budget exhausted ({br.failures} failures), {shed} "
                f"for {br.recovery_s:.0f}s (last: {err})")

    def degraded_plugins(self) -> list[str]:
        return sorted({pid for (pid, _), br in self.breakers.items()
                       if br.state != "closed"})

    def on(self, hook_name: str, handler: HookHandler, priority: int = 100, plugin_id: str = "?",
           never_shed: bool = False) -> None:
        self._seq += 1
        reg = _Registration(priority=priority, seq=self._seq, plugin_id=plugin_id,
                            handler=handler,
                            is_async=inspect.iscoroutinefunction(inspect.unwrap(handler)),
                            never_shed=never_shed)
        regs = self._handlers.setdefault(hook_name, [])
        regs.append(reg)
        regs.sort(key=lambda r: (r.priority, r.seq))
        self._invalidate(hook_name)

    def _invalidate(self, hook_name: str) -> None:
        """Drop per-hook dispatch caches after registration or an is_async
        promotion."""
        self._snapshots.pop(hook_name, None)
        self._async_memo.pop(hook_name, None)

    def handlers_for(self, hook_name: str) -> list[_Registration]:
        # Cached snapshot, rebuilt only when the registration set changes:
        # the per-fire list() copy (it guards against handlers registering
        # handlers mid-iteration) was a fixed tax on every enforcement call.
        # The cached list must be treated as immutable by callers.
        snap = self._snapshots.get(hook_name)
        if snap is None:
            snap = self._snapshots[hook_name] = list(self._handlers.get(hook_name, ()))
        return snap

    def has_async(self, hook_name: str) -> bool:
        memo = self._async_memo.get(hook_name)
        if memo is None:
            memo = self._async_memo[hook_name] = any(
                r.is_async for r in self._handlers.get(hook_name, ()))
        return memo

    @staticmethod
    async def _await_result(awaitable: Any) -> Any:
        return await awaitable

    @staticmethod
    def _close_awaitable(out: Any) -> None:
        """Best-effort close; Tasks/Futures/custom __await__ objects lack close()."""
        close = getattr(out, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001
                pass

    def _record(self, hook_name: str, error: Optional[str], n_errors: int = 0,
                n_skipped: int = 0) -> None:
        st = self.stats.setdefault(hook_name, HookStats())
        st.fired += 1
        st.last_fired_at = self._clock()
        if n_errors:
            st.errors += n_errors
            st.last_error = error
        if n_skipped:
            st.skipped += n_skipped

    async def fire(
        self,
        hook_name: str,
        *args: Any,
        until: Optional[Callable[[Any], bool]] = None,
        on_result: Optional[Callable[[Any], None]] = None,
        shed: bool = False,
    ) -> list[Any]:
        """Run all handlers in priority order; return their non-None results.

        ``until(result)`` short-circuits the chain when it returns True (used
        by the gateway for block verdicts). ``on_result`` is invoked after each
        non-None result so the caller can fold mutations (e.g. redacted params)
        into the shared event before the next handler sees it. ``shed=True``
        (admission control, ISSUE 6) skips every handler not registered
        ``never_shed`` — verdict-relevant handlers still run.
        """
        results: list[Any] = []
        err: Optional[str] = None
        n_errors = 0
        n_skipped = 0
        for reg in self.handlers_for(hook_name):
            if shed and not reg.never_shed:
                n_skipped += 1
                continue
            br = self._breaker_for(reg.plugin_id, hook_name)
            if (br is not None and hook_name not in NEVER_SHED_HOOKS
                    and not br.allow()):
                n_skipped += 1
                continue
            try:
                out = reg.handler(*args)
                if inspect.isawaitable(out):
                    if hook_name in SYNC_ONLY_HOOKS:
                        self._close_awaitable(out)
                        raise TypeError(
                            f"hook '{hook_name}' is synchronous; handler from "
                            f"plugin '{reg.plugin_id}' returned a coroutine"
                        )
                    out = await out
            except Exception as exc:  # noqa: BLE001 — plugins must not crash the gateway
                n_errors += 1
                err = f"{reg.plugin_id}/{hook_name}: {exc}"
                self._logger.error(f"[hook-bus] handler error in {err}")
                self._record_handler_failure(br, reg.plugin_id, hook_name, err)
                continue
            if br is not None:
                br.record_success()
            if out is not None:
                results.append(out)
                if on_result is not None:
                    on_result(out)
                if until is not None and until(out):
                    break
        self._record(hook_name, err, n_errors, n_skipped)
        return results

    def fire_sync(
        self,
        hook_name: str,
        *args: Any,
        until: Optional[Callable[[Any], bool]] = None,
        on_result: Optional[Callable[[Any], None]] = None,
        shed: bool = False,
    ) -> list[Any]:
        """Synchronous dispatch.

        A handler that unexpectedly returns an awaitable (sync lambda wrapping
        an async call, async ``__call__`` object — shapes registration-time
        detection can't see) is still honored on async-capable hooks: the
        awaitable is run to completion here and the registration is promoted
        so subsequent fires take the async path upfront. Sync-only hooks
        reject it, as ``fire`` does.
        """
        results: list[Any] = []
        err: Optional[str] = None
        n_errors = 0
        n_skipped = 0
        try:
            for reg in self.handlers_for(hook_name):
                if shed and not reg.never_shed:
                    n_skipped += 1
                    continue
                br = self._breaker_for(reg.plugin_id, hook_name)
                if (br is not None and hook_name not in NEVER_SHED_HOOKS
                        and not br.allow()):
                    n_skipped += 1
                    continue
                try:
                    out = reg.handler(*args)
                    if inspect.isawaitable(out):
                        if hook_name in SYNC_ONLY_HOOKS:
                            self._close_awaitable(out)
                            raise TypeError(
                                f"sync fire of '{hook_name}': handler from plugin "
                                f"'{reg.plugin_id}' is async"
                            )
                        reg.is_async = True
                        self._invalidate(hook_name)
                        try:
                            asyncio.get_running_loop()
                        except RuntimeError:
                            out = asyncio.run(self._await_result(out))
                        else:
                            self._close_awaitable(out)
                            raise SyncDispatchInAsyncContext(
                                f"hook '{hook_name}' handler from plugin "
                                f"'{reg.plugin_id}' returned an awaitable during a "
                                f"sync fire inside a running event loop; use the "
                                f"async gateway entry points"
                            )
                except SyncDispatchInAsyncContext:
                    # Fail loud (dropping a verdict here would fail open) —
                    # but settle the breaker first: a half-open probe slot
                    # consumed by allow() with no record_* afterwards would
                    # wedge the breaker in half-open forever.
                    if br is not None:
                        br.record_failure("awaitable during sync dispatch")
                    raise
                except Exception as exc:  # noqa: BLE001
                    n_errors += 1
                    err = f"{reg.plugin_id}/{hook_name}: {exc}"
                    self._logger.error(f"[hook-bus] handler error in {err}")
                    self._record_handler_failure(br, reg.plugin_id, hook_name, err)
                    continue
                if br is not None:
                    br.record_success()
                if out is not None:
                    results.append(out)
                    if on_result is not None:
                        on_result(out)
                    if until is not None and until(out):
                        break
        finally:
            self._record(hook_name, err, n_errors, n_skipped)
        return results


class PluginApi:
    """The per-plugin view handed to ``plugin.register(api)``.

    Field-for-field equivalent of the reference contract
    (governance/src/types.ts:10-26): ``id``, ``plugin_config``, ``logger``,
    ``config``, ``register_service``, ``register_command``,
    ``register_gateway_method``, ``on``.
    """

    def __init__(
        self,
        plugin_id: str,
        gateway: "Any",
        plugin_config: Optional[dict] = None,
        logger: Optional[PluginLogger] = None,
    ):
        self.id = plugin_id
        self.plugin_config = plugin_config or {}
        self.logger = logger or make_logger(plugin_id)
        self._gateway = gateway

    @property
    def config(self) -> dict:
        """The gateway-level config (openclaw.json equivalent)."""
        return self._gateway.config

    def register_service(self, service: PluginService) -> None:
        self._gateway._register_service(self.id, service)

    def register_command(self, command: PluginCommand) -> None:
        self._gateway._register_command(self.id, command)

    def register_gateway_method(self, method: str, handler: Callable[..., Any]) -> None:
        self._gateway._register_gateway_method(self.id, method, handler)

    def register_tool(self, tool: dict) -> None:
        """Optional agent-tool registration (reference: cortex/index.ts checks
        ``api.registerTool`` existence before registering its 5 tools)."""
        self._gateway._register_tool(self.id, tool)

    def register_stage_timer(self, name: str, timer: Any) -> None:
        """Publish a StageTimer into the gateway's observability registry
        (ISSUE 6): sitrep's stage-quantile/SLO collectors and the /ops
        command read every registered edge from one place instead of
        knowing each plugin's status shape."""
        self._gateway._register_stage_timer(self.id, name, timer)

    def register_journal(self, name: str, journal: Any) -> None:
        """Publish a group-commit journal into the gateway's observability
        registry (ISSUE 7): ``Gateway.get_status()["journal"]`` and sitrep's
        journal collector read pending/group-size/fsync/compaction/replay
        counters from one place. Plugins sharing a workspace journal all
        register the same instance under the same name — idempotent."""
        self._gateway._register_journal(self.id, name, journal)

    def register_lifecycle(self, name: str, manager: Any) -> None:
        """Publish a workspace LifecycleManager (ISSUE 11) into the
        gateway's observability registry: ``get_status()["lifecycle"]`` and
        sitrep's lifecycle collector read resident/hibernated counts, wake
        quantiles and eviction counters from one place."""
        self._gateway._register_lifecycle(self.id, name, manager)

    def unregister_stage_timer(self, name: str) -> None:
        """Drop a per-workspace registry entry at hibernation (ISSUE 11);
        the caller is responsible for absorbing the timer's histogram into
        an aggregate first if its quantiles should survive."""
        self._gateway._unregister_stage_timer(name)

    def unregister_journal(self, name: str) -> None:
        self._gateway._unregister_journal(name)

    def get_gateway_status(self) -> dict:
        """Public view of ``Gateway.get_status()`` (ISSUE 4's degradation
        surface) so plugin status commands can report degraded/breaker state
        for their own hooks without reaching through private gateway
        internals (ISSUE 5 satellite)."""
        return self._gateway.get_status()

    def on(self, hook_name: str, handler: HookHandler, priority: int = 100,
           never_shed: bool = False) -> None:
        self._gateway.bus.on(hook_name, handler, priority=priority, plugin_id=self.id,
                             never_shed=never_shed)

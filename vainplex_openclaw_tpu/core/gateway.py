"""The host gateway harness.

The reference suite assumes an *external* OpenClaw gateway and only ships a
test mock of it (``createMockApi`` with ``_fire`` —
openclaw-nats-eventstore/test/helpers.ts:21-35). Here the gateway host is a
first-class component: it loads plugins, owns the hook bus, runs service
lifecycles, dispatches commands and gateway RPC methods, and exposes typed
entry points for the flows that matter (tool calls, messages, sessions,
compaction). Everything is in-process, mirroring the reference's
single-event-loop execution model (SURVEY §3.1).

Hook result merging implemented here (reference: gateway-side semantics
reverse-engineered from handler return shapes, governance/src/types.ts:44-55
``HookBeforeToolCallResult {params?, block?, blockReason?}`` and the
response-gate fallback-message flow, governance/src/hooks.ts:339-353):

- ``before_tool_call``: first ``block`` verdict wins and stops the chain;
  ``params`` results replace the event's params for later handlers and for
  the tool itself.
- ``tool_result_persist``: synchronous; ``result`` mutations chain.
- ``message_sending`` / ``before_message_write``: ``content`` mutations chain;
  ``block`` stops the chain, optionally substituting ``fallback_message``.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..resilience.admission import AdmissionController
from .api import (
    ADMISSION_SHEDDABLE_HOOKS,
    HookBus,
    PluginApi,
    PluginCommand,
    PluginLogger,
    PluginService,
    make_logger,
)


@dataclass
class ToolCallDecision:
    blocked: bool
    block_reason: Optional[str]
    params: dict

    @property
    def allowed(self) -> bool:
        return not self.blocked


@dataclass
class MessageWriteDecision:
    blocked: bool
    content: str
    fallback_message: Optional[str] = None
    block_reason: Optional[str] = None

    @property
    def final_text(self) -> str:
        if self.blocked:
            return self.fallback_message or ""
        return self.content


@dataclass
class _LoadedPlugin:
    plugin_id: str
    api: PluginApi
    module: Any


def _run(coro):
    """Run a coroutine to completion from sync code (no nested loops)."""
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro)
    raise RuntimeError("use the async gateway methods inside an event loop")


class Gateway:
    """In-process host: plugin loader + hook dispatcher + service supervisor."""

    def __init__(
        self,
        config: Optional[dict] = None,
        logger: Optional[PluginLogger] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.config = config or {}
        self.logger = logger or make_logger("gateway")
        self.clock = clock
        self.bus = HookBus(
            self.logger, clock=clock,
            breaker_config=(self.config.get("resilience") or {}).get("pluginBreaker"))
        self.plugins: dict[str, _LoadedPlugin] = {}
        self.services: list[tuple[str, PluginService]] = []
        self.commands: dict[str, PluginCommand] = {}
        self.methods: dict[str, Callable[..., Any]] = {}
        self.tools: dict[str, dict] = {}
        # Observability registry (ISSUE 6): every serving edge publishes its
        # StageTimer here so sitrep/SLO surfaces read one place. In cluster
        # mode (ISSUE 9) every key is prefixed with the worker's id so the
        # supervisor's merged view can tell which worker's governance edge a
        # quantile belongs to — and strip the prefix to merge across workers.
        self.worker_prefix = str(
            ((self.config.get("cluster") or {}).get("workerPrefix")) or "")
        self.stage_timers: dict[str, Any] = {}
        # Journal registry (ISSUE 7): plugins publish their (shared)
        # group-commit journals; get_status() exports pending/group/fsync/
        # compaction/replay counters and sitrep's journal collector reads
        # them. Multiple plugins sharing one workspace journal register the
        # same name — last one wins, same instance either way.
        self.journals: dict[str, Any] = {}
        # Lifecycle registry (ISSUE 11): plugins publish their
        # LifecycleManagers (hibernation/wake accounting); get_status()
        # exports resident/hibernated/wake-quantile counters and sitrep's
        # lifecycle collector reads them.
        self.lifecycles: dict[str, Any] = {}
        # Admission control (ISSUE 6): None unless configured — seed
        # behavior is "never shed".
        self.admission = AdmissionController.from_config(
            (self.config.get("resilience") or {}).get("admission"))
        self._started = False

    # ── plugin registry ──────────────────────────────────────────────

    def load(self, plugin: Any, plugin_config: Optional[dict] = None,
             logger: Optional[PluginLogger] = None) -> PluginApi:
        """Load a plugin object exposing ``id`` and ``register(api)``."""
        plugin_id = getattr(plugin, "id", None) or getattr(plugin, "ID", None)
        if not plugin_id:
            raise ValueError("plugin must expose an 'id'")
        # Manifest validation (the openclaw.plugin.json equivalent): config
        # problems are warnings, never load failures — the gateway must boot.
        manifest = getattr(plugin, "manifest", None)
        if manifest is not None and plugin_config:
            for err in manifest.validate_config(plugin_config):
                (logger or self.logger).warn(f"[{plugin_id}] config schema: {err}")
        api = PluginApi(plugin_id, self, plugin_config=plugin_config, logger=logger)
        plugin.register(api)
        self.plugins[plugin_id] = _LoadedPlugin(plugin_id, api, plugin)
        return api

    def _register_service(self, plugin_id: str, service: PluginService) -> None:
        self.services.append((plugin_id, service))
        if self._started:
            self._start_service(plugin_id, service)

    def _register_command(self, plugin_id: str, command: PluginCommand) -> None:
        self.commands[command.name] = command

    def _register_gateway_method(self, plugin_id: str, method: str, handler: Callable[..., Any]) -> None:
        self.methods[method] = handler

    def _register_tool(self, plugin_id: str, tool: dict) -> None:
        self.tools[tool["name"]] = tool

    def _register_stage_timer(self, plugin_id: str, name: str, timer: Any) -> None:
        self.stage_timers[self.worker_prefix + name] = timer

    def _register_journal(self, plugin_id: str, name: str, journal: Any) -> None:
        self.journals[name] = journal

    def _register_lifecycle(self, plugin_id: str, name: str, manager: Any) -> None:
        self.lifecycles[name] = manager

    def _unregister_stage_timer(self, name: str) -> None:
        # Hibernation (ISSUE 11): a sleeping workspace's per-ws registry
        # entries are dropped so 10⁵ workspaces that spoke once don't pin
        # 10⁵ timers/journal objects in RAM forever; the lifecycle manager
        # absorbs the timer's histogram into its aggregate first.
        self.stage_timers.pop(self.worker_prefix + name, None)

    def _unregister_journal(self, name: str) -> None:
        self.journals.pop(name, None)

    # ── lifecycle ────────────────────────────────────────────────────

    def _start_service(self, plugin_id: str, service: PluginService) -> None:
        try:
            out = service.start(self)
            if asyncio.iscoroutine(out):
                _run(out)
        except Exception as exc:  # noqa: BLE001 — a bad service must not take the gateway down
            self.logger.error(f"[gateway] service {plugin_id}/{service.id} failed to start: {exc}")

    def start(self) -> None:
        self._started = True
        for plugin_id, service in self.services:
            self._start_service(plugin_id, service)
        self.fire("gateway_start", {}, {})

    def stop(self) -> None:
        self.fire("gateway_stop", {}, {})
        for plugin_id, service in reversed(self.services):
            if service.stop is None:
                continue
            try:
                out = service.stop(self)
                if asyncio.iscoroutine(out):
                    _run(out)
            except Exception as exc:  # noqa: BLE001
                self.logger.error(f"[gateway] service {plugin_id}/{service.id} failed to stop: {exc}")
        # Journals close LAST (ISSUE 7): plugin stop paths above flush
        # through them. Closing compacts + persists watermarks and releases
        # the wal fd; a later get_journal() on the same workspace opens a
        # fresh instance, and a straggler append falls back to its legacy
        # write path (append() returns False on a closed journal).
        for journal in self.journals.values():
            try:
                journal.close()
            except Exception as exc:  # noqa: BLE001 — stop paths can't raise
                self.logger.error(f"[gateway] journal close failed: {exc}")
        self._started = False

    # ── generic hook firing (the mock-api `_fire` equivalent) ────────

    def _shed(self, hook_name: str, args: tuple) -> bool:
        """Admission check (ISSUE 6): True → this hook fire is shed.
        Shedding is handler-granular: the bus still runs handlers
        registered ``never_shed`` (2FA code interception, trust feedback)
        and skips the rest (visible in the hook's ``skipped`` counter).
        Verdict-bearing hooks are not in ``ADMISSION_SHEDDABLE_HOOKS`` and
        never reach the controller. The tenant key is the ctx's workspace
        (one per SLO-harness tenant), falling back to session/agent
        identity."""
        if self.admission is None or hook_name not in ADMISSION_SHEDDABLE_HOOKS:
            return False
        ctx = args[1] if len(args) > 1 and isinstance(args[1], dict) else {}
        tenant = str(ctx.get("workspace") or ctx.get("session_key")
                     or ctx.get("agent_id") or "?")
        return not self.admission.admit(tenant)

    def _dispatch(self, hook_name: str, *args: Any, until=None, on_result=None) -> list[Any]:
        """Single sync-vs-async dispatch decision: hooks with only sync
        handlers skip the event loop entirely (the enforcement/ingest hot
        paths are sync in the common case)."""
        shed = self._shed(hook_name, args)
        if self.bus.has_async(hook_name):
            return _run(self.bus.fire(hook_name, *args, until=until,
                                      on_result=on_result, shed=shed))
        return self.bus.fire_sync(hook_name, *args, until=until,
                                  on_result=on_result, shed=shed)

    def fire(self, hook_name: str, *args: Any) -> list[Any]:
        return self._dispatch(hook_name, *args)

    async def fire_async(self, hook_name: str, *args: Any) -> list[Any]:
        return await self.bus.fire(hook_name, *args,
                                   shed=self._shed(hook_name, args))

    # ── typed flows ──────────────────────────────────────────────────

    @staticmethod
    def _tool_call_fixture(tool_name: str, params: dict, ctx: Optional[dict]):
        event = {"tool_name": tool_name, "params": dict(params)}
        ctx = dict(ctx or {})
        ctx.setdefault("tool_name", tool_name)

        def fold(result: Any) -> None:
            if isinstance(result, dict) and result.get("params") is not None:
                event["params"] = result["params"]

        def is_block(r: Any) -> bool:
            return isinstance(r, dict) and bool(r.get("block"))

        return event, ctx, fold, is_block

    @staticmethod
    def _tool_call_decision(results: list[Any], event: dict) -> ToolCallDecision:
        for r in results:
            if isinstance(r, dict) and r.get("block"):
                return ToolCallDecision(True, r.get("block_reason") or r.get("blockReason"), event["params"])
        return ToolCallDecision(False, None, event["params"])

    async def before_tool_call_async(self, tool_name: str, params: dict,
                                     ctx: Optional[dict] = None) -> ToolCallDecision:
        event, ctx, fold, is_block = self._tool_call_fixture(tool_name, params, ctx)
        results = await self.bus.fire("before_tool_call", event, ctx, until=is_block, on_result=fold)
        return self._tool_call_decision(results, event)

    def before_tool_call(self, tool_name: str, params: dict, ctx: Optional[dict] = None) -> ToolCallDecision:
        event, fctx, fold, is_block = self._tool_call_fixture(tool_name, params, ctx)
        results = self._dispatch("before_tool_call", event, fctx, until=is_block, on_result=fold)
        return self._tool_call_decision(results, event)

    def after_tool_call(self, tool_name: str, params: dict, result: Any = None,
                        error: Optional[str] = None, ctx: Optional[dict] = None) -> None:
        event = {"tool_name": tool_name, "params": params, "result": result, "error": error}
        ctx = dict(ctx or {})
        ctx.setdefault("tool_name", tool_name)
        self.fire("after_tool_call", event, ctx)

    def tool_result_persist(self, tool_name: str, result: Any, ctx: Optional[dict] = None) -> Any:
        """Synchronous mutation point before a tool result enters LLM context
        (reference: redaction Layer 1, redaction/hooks.ts:33-47)."""
        event = {"tool_name": tool_name, "result": result}
        ctx = dict(ctx or {})
        ctx.setdefault("tool_name", tool_name)

        def fold(r: Any) -> None:
            if isinstance(r, dict) and "result" in r:
                event["result"] = r["result"]

        self.bus.fire_sync("tool_result_persist", event, ctx, on_result=fold)
        return event["result"]

    def run_tool(self, tool_name: str, params: dict, fn: Callable[[dict], Any],
                 ctx: Optional[dict] = None) -> tuple[ToolCallDecision, Any]:
        """Full tool round-trip: before → execute → persist-mutate → after."""
        decision = self.before_tool_call(tool_name, params, ctx)
        if decision.blocked:
            self.after_tool_call(tool_name, params, None, error=f"blocked: {decision.block_reason}", ctx=ctx)
            return decision, None
        try:
            raw = fn(decision.params)
            err = None
        except Exception as exc:  # noqa: BLE001 — tool failures flow into after_tool_call as errors
            raw, err = None, str(exc)
        persisted = self.tool_result_persist(tool_name, raw, ctx) if err is None else None
        self.after_tool_call(tool_name, decision.params, persisted, error=err, ctx=ctx)
        return decision, persisted

    def message_received(self, content: str, ctx: Optional[dict] = None) -> list[Any]:
        return self.fire("message_received", {"content": content}, dict(ctx or {}))

    def message_sending(self, content: str, ctx: Optional[dict] = None) -> MessageWriteDecision:
        return self._outbound("message_sending", content, ctx, sync=False)

    def before_message_write(self, content: str, ctx: Optional[dict] = None) -> MessageWriteDecision:
        return self._outbound("before_message_write", content, ctx, sync=True)

    def message_sent(self, content: str, ctx: Optional[dict] = None) -> list[Any]:
        return self.fire("message_sent", {"content": content}, dict(ctx or {}))

    def _outbound(self, hook: str, content: str, ctx: Optional[dict], sync: bool) -> MessageWriteDecision:
        event = {"content": content}
        ctx = dict(ctx or {})

        def fold(r: Any) -> None:
            if isinstance(r, dict) and r.get("content") is not None:
                event["content"] = r["content"]

        def is_block(r: Any) -> bool:
            return isinstance(r, dict) and bool(r.get("block"))

        if sync:
            results = self.bus.fire_sync(hook, event, ctx, until=is_block, on_result=fold)
        else:
            results = self._dispatch(hook, event, ctx, until=is_block, on_result=fold)
        for r in results:
            if is_block(r):
                return MessageWriteDecision(True, event["content"],
                                            r.get("fallback_message") or r.get("fallbackMessage"),
                                            r.get("block_reason") or r.get("blockReason"))
        return MessageWriteDecision(False, event["content"])

    def fire_results(self, hook: str, *args: Any, until=None, on_result=None) -> list[Any]:
        return self._dispatch(hook, *args, until=until, on_result=on_result)

    def session_start(self, ctx: Optional[dict] = None) -> list[Any]:
        return self.fire("session_start", {}, dict(ctx or {}))

    def session_end(self, ctx: Optional[dict] = None) -> list[Any]:
        return self.fire("session_end", {}, dict(ctx or {}))

    def before_agent_start(self, ctx: Optional[dict] = None) -> list[Any]:
        """Returns context-injection results (``{prepend_context: str}``)."""
        return self.fire("before_agent_start", {}, dict(ctx or {}))

    def agent_end(self, ctx: Optional[dict] = None, error: Optional[str] = None,
                  final_message: Optional[str] = None) -> list[Any]:
        return self.fire("agent_end", {"error": error, "final_message": final_message},
                         dict(ctx or {}))

    def before_compaction(self, ctx: Optional[dict] = None,
                          messages: Optional[list] = None) -> list[Any]:
        return self.fire("before_compaction", {"messages": messages or []}, dict(ctx or {}))

    def after_compaction(self, ctx: Optional[dict] = None,
                         kept_messages: int = 0) -> list[Any]:
        return self.fire("after_compaction", {"kept_messages": kept_messages},
                         dict(ctx or {}))

    def llm_input(self, prompt: str, ctx: Optional[dict] = None) -> list[Any]:
        """Observation hook; the event store records lengths only, never bodies."""
        return self.fire("llm_input", {"prompt": prompt}, dict(ctx or {}))

    def llm_output(self, completion: str, ctx: Optional[dict] = None) -> list[Any]:
        return self.fire("llm_output", {"completion": completion}, dict(ctx or {}))

    # ── commands & RPC ───────────────────────────────────────────────

    def command(self, name: str, ctx: Optional[dict] = None, args: str = "") -> dict:
        cmd = self.commands.get(name.lstrip("/"))
        if cmd is None:
            return {"text": f"unknown command: {name}"}
        try:
            out = cmd.handler({"args": args, **(ctx or {})})
            if asyncio.iscoroutine(out):
                out = _run(out)
            return out
        except Exception as exc:  # noqa: BLE001
            return {"text": f"command {name} failed: {exc}"}

    def call_method(self, method: str, *args: Any) -> Any:
        handler = self.methods.get(method)
        if handler is None:
            raise KeyError(f"unknown gateway method: {method}")
        return handler(*args)

    # ── status ───────────────────────────────────────────────────────

    def get_status(self) -> dict:
        """Degradation surface (ISSUE 4): which plugins are shedding, which
        hooks skipped handlers, and every tripped breaker's counters."""
        hooks = {name: {"fired": st.fired, "errors": st.errors,
                        "skipped": st.skipped}
                 for name, st in self.bus.stats.items()}
        breakers: dict[str, dict] = {}
        for (pid, hook), br in self.bus.breakers.items():
            if br.failures or br.state != "closed":
                breakers.setdefault(pid, {})[hook] = br.stats()
        return {
            "started": self._started,
            "plugins": sorted(self.plugins),
            "degraded": self.bus.degraded_plugins(),
            "breakers": breakers,
            "hooks": hooks,
            "admission": (self.admission.stats() if self.admission is not None
                          else {"enabled": False}),
            "journal": {name: j.stats() for name, j in self.journals.items()},
            "lifecycle": {name: m.stats()
                          for name, m in self.lifecycles.items()},
        }

"""Is it safe to let jax initialize a backend in THIS process?

Initializing the default backend resolves and initializes EVERY registered
platform plugin. A remote-accelerator plugin (the axon TPU tunnel on this
image) can block forever inside its client init when the tunnel is wedged —
no exception fires, the calling thread just stops (observed live in round
5: the analyzer's clustering stage hung the whole bench budget).

Safe means one of:
- the process pinned its platform set to LOCAL platforms only — in
  practice ``jax.config.update("jax_platforms", "cpu")`` before first
  init (what the test conftest, bench.py, and force-CPU entry points all
  do). A merely *pinned* set is NOT enough: this image presets
  ``jax_platforms='axon,cpu'`` at plugin registration, and initializing
  that set is exactly the hang. Local backends cannot wedge; remote ones
  can, at init time or any dispatch after.
- the operator explicitly accepted default/remote-backend init via
  ``OPENCLAW_ALLOW_DEFAULT_BACKEND=1`` (or the older
  ``OPENCLAW_SIMILARITY_DEVICE=default``), taking on the hang risk.

AUTO features that would otherwise silently pull jax into a
latency-sensitive process (analyzer batch kernels, local-triage
auto-enable) consult this and degrade instead of gambling. Explicitly
configured jax features (``useLocalTriage: true``, the local embeddings
backend) are an operator's deliberate choice and are not gated.
"""

from __future__ import annotations

import os
from typing import Optional

# 'tpu' is local libtpu — initialized in-process over PCIe, no tunnel to
# wedge — so a jax_platforms='tpu' pin is as safe as 'cpu'. (ADVICE r5: the
# serve path's error message advertised jax_platforms='tpu' while this set
# rejected it, making the advertised remedy a dead end.) The remote 'axon'
# plugin is exactly what this set exists to exclude.
_LOCAL_PLATFORMS = {"cpu", "tpu"}


def backend_init_safe() -> bool:
    if os.environ.get("OPENCLAW_ALLOW_DEFAULT_BACKEND") == "1":
        return True
    if os.environ.get("OPENCLAW_SIMILARITY_DEVICE") == "default":
        return True
    try:
        import jax

        platforms = jax.config.jax_platforms
    except Exception:  # noqa: BLE001 — no jax → nothing to initialize
        return False
    if not platforms:
        return False
    names = {p.strip().lower() for p in str(platforms).split(",") if p.strip()}
    return bool(names) and names <= _LOCAL_PLATFORMS


def enable_persistent_compilation_cache(cache_dir: Optional[str] = None) -> bool:
    """Opt-in persistent XLA compilation cache; returns True if enabled.

    No-op unless ``cache_dir`` is passed or $OPENCLAW_XLA_CACHE_DIR is set —
    writing compiled executables to disk is an operator decision, not a
    default. Once on, every jit compile is written through to the cache
    directory and replayed on the next process with the same fingerprint.
    Two workloads this de-risks:

    - the encoder_mfu ladder (bench.py/tpu_capture.py): the level-0 remote
      compile has never fit a healthy tunnel window live — with the cache,
      a compile that finished in ANY previous attempt is a disk read;
    - repeated CPU bench/CI runs of the similarity kernels, whose
      power-of-two-bucketed shapes are stable across runs by design.

    The min-compile-time/entry-size floors are dropped to zero so even the
    small bucketed kernels persist; flags missing from older jax versions
    are skipped rather than fatal.
    """
    cache_dir = cache_dir or os.environ.get("OPENCLAW_XLA_CACHE_DIR")
    if not cache_dir:
        return False
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    except Exception:  # noqa: BLE001 — no jax / unsupported: feature stays off
        return False
    for flag, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(flag, val)
        except Exception:  # noqa: BLE001 — flag not in this jax version
            pass
    return True

"""Is it safe to let jax initialize a backend in THIS process?

Initializing the default backend resolves and initializes EVERY registered
platform plugin. A remote-accelerator plugin (the axon TPU tunnel on this
image) can block forever inside its client init when the tunnel is wedged —
no exception fires, the calling thread just stops (observed live in round
5: the analyzer's clustering stage hung the whole bench budget).

Safe means one of:
- the process pinned its platform set to LOCAL platforms only — in
  practice ``jax.config.update("jax_platforms", "cpu")`` before first
  init (what the test conftest, bench.py, and force-CPU entry points all
  do). A merely *pinned* set is NOT enough: this image presets
  ``jax_platforms='axon,cpu'`` at plugin registration, and initializing
  that set is exactly the hang. Local backends cannot wedge; remote ones
  can, at init time or any dispatch after.
- the operator explicitly accepted default/remote-backend init via
  ``OPENCLAW_ALLOW_DEFAULT_BACKEND=1`` (or the older
  ``OPENCLAW_SIMILARITY_DEVICE=default``), taking on the hang risk.

AUTO features that would otherwise silently pull jax into a
latency-sensitive process (analyzer batch kernels, local-triage
auto-enable) consult this and degrade instead of gambling. Explicitly
configured jax features (``useLocalTriage: true``, the local embeddings
backend) are an operator's deliberate choice and are not gated.
"""

from __future__ import annotations

import os

_LOCAL_PLATFORMS = {"cpu"}


def backend_init_safe() -> bool:
    if os.environ.get("OPENCLAW_ALLOW_DEFAULT_BACKEND") == "1":
        return True
    if os.environ.get("OPENCLAW_SIMILARITY_DEVICE") == "default":
        return True
    try:
        import jax

        platforms = jax.config.jax_platforms
    except Exception:  # noqa: BLE001 — no jax → nothing to initialize
        return False
    if not platforms:
        return False
    names = {p.strip().lower() for p in str(platforms).split(",") if p.strip()}
    return bool(names) and names <= _LOCAL_PLATFORMS

"""Shared small utilities."""

from .llm_json import parse_llm_json

__all__ = ["parse_llm_json"]

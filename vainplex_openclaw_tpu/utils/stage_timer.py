"""Lightweight wall-clock stage breakdown for multi-stage pipelines.

Built for the trace analyzer's hot path (VERDICT r5 weak #2: the headline
throughput halved and nothing on record could say WHICH stage ate it), but
deliberately generic: name stages, wrap them in ``stage()``, read the
breakdown as a dict. Overhead is two ``perf_counter`` calls per stage —
nothing here may tax the path it is measuring.

ISSUE 6 adds latency *distributions* on the same budget: every ``add()``
also increments one bucket of a log2 histogram (a ``math.frexp`` call plus
a list increment — no allocation, no sort, no reservoir), so status
surfaces and the SLO harness can read p50/p95/p99 per stage instead of
only means. ``snapshot()`` returns everything — accumulated ms, counts,
quantiles — under ONE lock round-trip, replacing the torn
``stages_ms()``-then-``counts()`` read pattern on status paths.
"""

from __future__ import annotations

import math
import threading
import time
from contextlib import contextmanager
from typing import Callable, Iterable

# Log2 histogram geometry. Bucket ``i`` covers [2^(e-1), 2^e) milliseconds
# with e = i + _HIST_MIN_EXP; bucket 0 additionally absorbs everything
# below ~0.5 µs (including 0 and negative clock skew), the top bucket
# everything above ~2^20 ms (~17.5 min). 33 ints per stage, fixed.
_HIST_MIN_EXP = -11
_HIST_MAX_EXP = 21
HIST_BUCKETS = _HIST_MAX_EXP - _HIST_MIN_EXP + 1
_HIST_TOP = HIST_BUCKETS - 1
_frexp = math.frexp  # bound once: the lookup is visible on the hot path


def _bucket_of(ms: float) -> int:
    """O(1) bucket index for a duration in ms (frexp, no log call)."""
    if ms <= 0.0:
        return 0
    e = _frexp(ms)[1]  # ms ∈ [2^(e-1), 2^e)
    if e <= _HIST_MIN_EXP:
        return 0
    if e > _HIST_MAX_EXP:
        return _HIST_TOP
    return e - _HIST_MIN_EXP


def _bucket_bounds(idx: int) -> tuple[float, float]:
    """[lo, hi) in ms covered by bucket ``idx`` (bucket 0 starts at 0)."""
    e = idx + _HIST_MIN_EXP
    lo = 0.0 if idx == 0 else 2.0 ** (e - 1)
    return lo, 2.0 ** e


def quantile_label(q: float) -> str:
    """0.5 → 'p50', 0.99 → 'p99', 0.999 → 'p99.9'."""
    pct = q * 100.0
    return f"p{pct:g}"


def estimate_quantiles(hist: list, qs: Iterable[float],
                       precision: int = 4) -> dict:
    """Bounded-error quantile estimates from one log2 histogram.

    For each q the estimate lands in the bucket holding the rank-
    ``floor(q*(n-1))`` sample (numpy's ``percentile(..., method='lower')``
    rank rule) and interpolates linearly inside it, so for samples inside
    the histogram range (~0.5 µs … ~2^21 ms ≈ 35 min) the estimate is
    always within one bucket of the true sample quantile: at most a
    factor of 2 off, in practice far closer (pinned by the property
    suite in tests against ``numpy.percentile``). Samples OUTSIDE the
    range clamp into the edge buckets, so a quantile landing there is
    reported as the edge bucket's value — a >35-minute hang reads as
    "≥ the top bucket", not its true magnitude.
    """
    n = sum(hist)
    out: dict[str, float] = {}
    if n == 0:
        return {quantile_label(q): 0.0 for q in qs}
    for q in qs:
        rank = int(q * (n - 1))  # 0-based index of the target order stat
        cum = 0
        idx = HIST_BUCKETS - 1
        for i, c in enumerate(hist):
            if cum + c > rank:
                idx = i
                break
            cum += c
        lo, hi = _bucket_bounds(idx)
        inside = hist[idx] or 1
        frac = (rank - cum + 0.5) / inside
        out[quantile_label(q)] = round(lo + frac * (hi - lo), precision)
    return out


DEFAULT_QUANTILES = (0.5, 0.95, 0.99)


class StageTimer:
    """Accumulates per-stage wall-clock milliseconds in stage-entry order.

    Re-entering a stage name accumulates (a stage split across code paths
    still reads as one line in the breakdown). ``clock`` is injectable for
    tests; it must be a monotonic seconds counter. Accumulation is guarded
    by a lock: the knowledge plugin shares one timer between the serve
    thread and the maintenance daemon, and an unguarded read-modify-write
    would silently drop updates from the attribution it exists to provide.

    Every sample also lands in a per-stage log2 latency histogram, read
    back through ``quantiles()`` / ``snapshot()``.
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._ms: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._hist: dict[str, list] = {}

    @contextmanager
    def stage(self, name: str):
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(name, (self._clock() - t0) * 1000.0)

    def add(self, name: str, ms: float) -> None:
        # ``_bucket_of`` inlined (one frexp, outside the lock): this is
        # THE hot path — ≤5% histogram overhead on the compiled edges is
        # an acceptance bound (docs/observability.md carries the A/B).
        # ``add_many`` calls the helper; the add-vs-add_many histogram
        # equality test pins the two against drift.
        if ms > 0.0:
            e = _frexp(ms)[1]
            idx = (0 if e <= _HIST_MIN_EXP
                   else _HIST_TOP if e > _HIST_MAX_EXP
                   else e - _HIST_MIN_EXP)
        else:
            idx = 0
        with self._lock:
            self._ms[name] = self._ms.get(name, 0.0) + ms
            self._counts[name] = self._counts.get(name, 0) + 1
            try:
                self._hist[name][idx] += 1
            except KeyError:  # first sample for this stage
                self._hist[name] = hist = [0] * HIST_BUCKETS
                hist[idx] += 1

    # ``record`` is the ISSUE-6 name for the same operation: two
    # perf_counter calls (in ``stage``) + one bucket increment.
    record = add

    def add_many(self, items) -> None:
        """Accumulate several (name, ms) pairs under one lock round-trip —
        for per-request pipelines (governance enforcement) where six
        separate acquisitions would tax the path being attributed."""
        with self._lock:
            for name, ms in items:
                self._ms[name] = self._ms.get(name, 0.0) + ms
                self._counts[name] = self._counts.get(name, 0) + 1
                idx = _bucket_of(ms)
                try:
                    self._hist[name][idx] += 1
                except KeyError:
                    self._hist[name] = hist = [0] * HIST_BUCKETS
                    hist[idx] += 1

    def stages_ms(self, precision: int = 2) -> dict:
        """Fresh {stage: rounded ms} dict in stage-entry order."""
        with self._lock:
            return {k: round(v, precision) for k, v in self._ms.items()}

    def counts(self) -> dict:
        """Fresh {stage: entries} dict in stage-entry order — accumulated ms
        alone can't distinguish one slow call from many fast ones (the
        knowledge engine's ingest/search attribution needs per-call cost)."""
        with self._lock:
            return dict(self._counts)

    def total_ms(self) -> float:
        with self._lock:
            return sum(self._ms.values())

    def quantiles(self, qs: Iterable[float] = DEFAULT_QUANTILES,
                  precision: int = 4) -> dict:
        """{stage: {"p50": ms, ...}} bounded-error latency estimates from
        the log2 histograms (see ``estimate_quantiles`` for the bound)."""
        qs = tuple(qs)  # a one-shot iterator must serve every stage
        with self._lock:
            hists = {k: list(h) for k, h in self._hist.items()}
        return {k: estimate_quantiles(h, qs, precision) for k, h in hists.items()}

    def state(self) -> dict:
        """Mergeable (and picklable) raw state: accumulated ms, counts and
        the log2 histograms themselves. This is the cross-worker aggregation
        seam (ISSUE 9): a cluster worker ships ``state()`` over a pipe and
        the supervisor ``absorb()``s it — histograms add bucket-wise, so the
        merged quantiles are exactly what one timer observing all workers'
        samples would have estimated (unlike merging the already-estimated
        per-worker quantiles, which has no defensible semantics)."""
        with self._lock:
            return {"ms": dict(self._ms), "counts": dict(self._counts),
                    "hist": {k: list(h) for k, h in self._hist.items()}}

    def absorb(self, state: dict) -> None:
        """Merge another timer's ``state()`` into this one (bucket-wise)."""
        ms, counts, hist = state["ms"], state["counts"], state["hist"]
        with self._lock:
            for k, v in ms.items():
                self._ms[k] = self._ms.get(k, 0.0) + v
            for k, v in counts.items():
                self._counts[k] = self._counts.get(k, 0) + v
            for k, h in hist.items():
                mine = self._hist.get(k)
                if mine is None:
                    self._hist[k] = list(h)
                else:
                    for i, c in enumerate(h):
                        mine[i] += c

    def snapshot(self, precision: int = 2,
                 qs: Iterable[float] = DEFAULT_QUANTILES) -> dict:
        """Consistent one-lock view for status surfaces:
        ``{"stages_ms", "counts", "total_ms", "quantiles"}``.

        Status paths that used to call ``stages_ms()`` then ``counts()``
        back-to-back could observe a sample that landed between the two
        reads — ms and counts attributing different traffic. Quantile
        estimation happens on copies, outside the lock."""
        qs = tuple(qs)
        with self._lock:
            raw_ms = dict(self._ms)
            counts = dict(self._counts)
            hists = {k: list(h) for k, h in self._hist.items()}
        return {
            "stages_ms": {k: round(v, precision) for k, v in raw_ms.items()},
            "counts": counts,
            "total_ms": round(sum(raw_ms.values()), precision),
            "quantiles": {k: estimate_quantiles(h, qs)
                          for k, h in hists.items()},
        }

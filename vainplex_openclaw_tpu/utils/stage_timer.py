"""Lightweight wall-clock stage breakdown for multi-stage pipelines.

Built for the trace analyzer's hot path (VERDICT r5 weak #2: the headline
throughput halved and nothing on record could say WHICH stage ate it), but
deliberately generic: name stages, wrap them in ``stage()``, read the
breakdown as a dict. Overhead is two ``perf_counter`` calls per stage —
nothing here may tax the path it is measuring.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Callable


class StageTimer:
    """Accumulates per-stage wall-clock milliseconds in stage-entry order.

    Re-entering a stage name accumulates (a stage split across code paths
    still reads as one line in the breakdown). ``clock`` is injectable for
    tests; it must be a monotonic seconds counter.
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._ms: dict[str, float] = {}

    @contextmanager
    def stage(self, name: str):
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(name, (self._clock() - t0) * 1000.0)

    def add(self, name: str, ms: float) -> None:
        self._ms[name] = self._ms.get(name, 0.0) + ms

    def stages_ms(self, precision: int = 2) -> dict:
        """Fresh {stage: rounded ms} dict in stage-entry order."""
        return {k: round(v, precision) for k, v in self._ms.items()}

    def total_ms(self) -> float:
        return sum(self._ms.values())

"""Lightweight wall-clock stage breakdown for multi-stage pipelines.

Built for the trace analyzer's hot path (VERDICT r5 weak #2: the headline
throughput halved and nothing on record could say WHICH stage ate it), but
deliberately generic: name stages, wrap them in ``stage()``, read the
breakdown as a dict. Overhead is two ``perf_counter`` calls per stage —
nothing here may tax the path it is measuring.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Callable


class StageTimer:
    """Accumulates per-stage wall-clock milliseconds in stage-entry order.

    Re-entering a stage name accumulates (a stage split across code paths
    still reads as one line in the breakdown). ``clock`` is injectable for
    tests; it must be a monotonic seconds counter. Accumulation is guarded
    by a lock: the knowledge plugin shares one timer between the serve
    thread and the maintenance daemon, and an unguarded read-modify-write
    would silently drop updates from the attribution it exists to provide.
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._ms: dict[str, float] = {}
        self._counts: dict[str, int] = {}

    @contextmanager
    def stage(self, name: str):
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(name, (self._clock() - t0) * 1000.0)

    def add(self, name: str, ms: float) -> None:
        with self._lock:
            self._ms[name] = self._ms.get(name, 0.0) + ms
            self._counts[name] = self._counts.get(name, 0) + 1

    def add_many(self, items) -> None:
        """Accumulate several (name, ms) pairs under one lock round-trip —
        for per-request pipelines (governance enforcement) where six
        separate acquisitions would tax the path being attributed."""
        with self._lock:
            for name, ms in items:
                self._ms[name] = self._ms.get(name, 0.0) + ms
                self._counts[name] = self._counts.get(name, 0) + 1

    def stages_ms(self, precision: int = 2) -> dict:
        """Fresh {stage: rounded ms} dict in stage-entry order."""
        with self._lock:
            return {k: round(v, precision) for k, v in self._ms.items()}

    def counts(self) -> dict:
        """Fresh {stage: entries} dict in stage-entry order — accumulated ms
        alone can't distinguish one slow call from many fast ones (the
        knowledge engine's ingest/search attribution needs per-call cost)."""
        with self._lock:
            return dict(self._counts)

    def total_ms(self) -> float:
        with self._lock:
            return sum(self._ms.values())

"""Strict-JSON parsing of LLM output, tolerant of the two failure shapes
every LLM JSON contract hits: markdown code fences (with or without a
language tag) and surrounding prose. One implementation for every LLM seam
(governance stage-3 validator, cortex enhancer, trace-analyzer classifier).
"""

from __future__ import annotations

import json
from typing import Optional


def parse_llm_json(raw: str) -> Optional[dict]:
    """Return the first JSON object in ``raw`` or None."""
    if not isinstance(raw, str):
        return None
    text = raw.strip()
    if text.startswith("```"):
        text = "\n".join(line for line in text.splitlines()
                         if not line.strip().startswith("```")).strip()
    try:
        parsed = json.loads(text)
    except json.JSONDecodeError:
        start, end = text.find("{"), text.rfind("}")
        if start == -1 or end <= start:
            return None
        try:
            parsed = json.loads(text[start:end + 1])
        except json.JSONDecodeError:
            return None
    return parsed if isinstance(parsed, dict) else None

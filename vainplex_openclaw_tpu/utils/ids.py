"""Shared PRNG-backed UUID4 generation for correlation ids.

Audit records, knowledge facts, and cortex threads/decisions/commitments all
need uuid4-FORMATTED ids but none of them need capability-token entropy —
``uuid.uuid4()`` pays a urandom syscall per call (and building a
``uuid.UUID`` object just to ``str()`` it doubles the cost again). One
module-level PRNG, seeded once from ``os.urandom`` and reseeded after fork
so child processes can't replay the parent's id stream, serves all three
(previously three private copies of the same bit-twiddling)."""

from __future__ import annotations

import os
import random

_ID_RNG = random.Random(int.from_bytes(os.urandom(16), "big"))

if hasattr(os, "register_at_fork"):
    os.register_at_fork(
        after_in_child=lambda: _ID_RNG.seed(int.from_bytes(os.urandom(16), "big")))


def prng_uuid4() -> str:
    # Hand-formatted RFC-4122 v4 layout (version nibble 4, variant bits 10).
    v = _ID_RNG.getrandbits(128)
    v = (v & ~(0xF << 76) | (4 << 76)) & ~(0x3 << 62) | (0x2 << 62)
    s = f"{v:032x}"
    return f"{s[:8]}-{s[8:12]}-{s[12:16]}-{s[16:20]}-{s[20:]}"

"""Config conventions shared by every plugin."""

from .loader import deep_merge, load_plugin_config, plugins_dir

__all__ = ["deep_merge", "load_plugin_config", "plugins_dir"]

"""External-config loading with bootstrap-write.

Reference semantics (governance/src/config-loader.ts:7-35,78-…, duplicated in
cortex/src/config-loader.ts and nats-eventstore):

- The gateway's own config carries only a minimal inline pointer per plugin:
  ``{"enabled": bool, "configPath": "..."}``.
- The full config lives at ``~/.openclaw/plugins/<id>/config.json`` (or at the
  explicit ``configPath``), bootstrap-written with defaults on first run.
- Legacy-inline heuristic: an inline config with substantive keys beyond
  ``enabled``/``configPath`` is treated as the full config (older installs
  embedded everything inline).
- All resolution is fail-open: unreadable/invalid external files fall back to
  defaults with a warning, never an exception.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Optional

from ..core.api import PluginLogger, make_logger
from ..storage.atomic import read_json, write_json_atomic

_POINTER_KEYS = {"enabled", "configPath", "config_path"}


def plugins_dir(home: Optional[str | Path] = None) -> Path:
    home = Path(home) if home else Path(os.environ.get("OPENCLAW_HOME") or (Path.home() / ".openclaw"))
    return home / "plugins"


def deep_merge(defaults: Any, override: Any) -> Any:
    """Deep-default: every key in ``defaults`` survives unless overridden."""
    if isinstance(defaults, dict) and isinstance(override, dict):
        out = dict(defaults)
        for k, v in override.items():
            out[k] = deep_merge(defaults.get(k), v) if k in defaults else v
        return out
    return defaults if override is None else override


def _is_legacy_inline(inline: dict) -> bool:
    return any(k not in _POINTER_KEYS for k in inline)


def load_plugin_config(
    plugin_id: str,
    inline: Optional[dict] = None,
    defaults: Optional[dict] = None,
    home: Optional[str | Path] = None,
    logger: Optional[PluginLogger] = None,
    bootstrap: bool = True,
) -> dict:
    """Resolve a plugin's full config; returns defaults ⊕ external ⊕ inline."""
    logger = logger or make_logger(plugin_id)
    inline = dict(inline or {})
    defaults = dict(defaults or {})
    enabled = bool(inline.get("enabled", True))

    if _is_legacy_inline(inline):
        merged = deep_merge(defaults, {k: v for k, v in inline.items() if k not in _POINTER_KEYS})
        merged["enabled"] = enabled
        return merged

    config_path = inline.get("configPath") or inline.get("config_path")
    path = Path(config_path) if config_path else plugins_dir(home) / plugin_id / "config.json"

    external: Optional[dict] = None
    if path.exists():
        try:
            loaded = json.loads(path.read_text(encoding="utf-8"))
            if isinstance(loaded, dict):
                external = loaded
            else:
                logger.warn(f"config at {path} is not an object; using defaults")
        except (OSError, json.JSONDecodeError) as exc:
            logger.warn(f"failed to read config at {path}: {exc}; using defaults")
    elif bootstrap:
        try:
            write_json_atomic(path, defaults)
            logger.info(f"bootstrapped default config at {path}")
        except OSError as exc:
            logger.warn(f"could not bootstrap config at {path}: {exc}")

    merged = deep_merge(defaults, external or {})
    # The inline pointer's enabled:false always wins: an operator who disabled
    # a plugin in openclaw.json must not have it re-enabled by the external
    # file (including the bootstrap-written defaults, which carry enabled:true).
    external_enabled = bool(external.get("enabled", True)) if external else True
    merged["enabled"] = enabled and external_enabled
    return merged


def read_openclaw_config(home: Optional[str | Path] = None) -> dict:
    """Read the gateway-level ``openclaw.json`` (empty dict if absent)."""
    home = Path(home) if home else Path(os.environ.get("OPENCLAW_HOME") or (Path.home() / ".openclaw"))
    return read_json(home / "openclaw.json", {}) or {}

"""Plugin manifests: the ``openclaw.plugin.json`` equivalent.

The reference ships a JSON-schema'd manifest per plugin
(``openclaw.plugin.json``, SURVEY §5 "Config / flag system": per-plugin
manifest + external config + bootstrap-write). Here the manifest is a
first-class object each plugin exposes as ``MANIFEST``; the gateway
validates supplied plugin config against it at load time (warn-only —
config problems must never crash the gateway) and ``brainplex`` validates
the configs it generates.

The schema dialect is the small JSON-Schema subset the reference manifests
actually use: ``type`` (object/array/string/number/integer/boolean/null),
``properties``/``required``/``additionalProperties``, ``items``, ``enum``,
``minimum``/``maximum``, and union types via a list in ``type``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

_TYPE_CHECKS = {
    "object": lambda v: isinstance(v, dict),
    "array": lambda v: isinstance(v, (list, tuple)),
    "string": lambda v: isinstance(v, str),
    "number": lambda v: isinstance(v, (int, float)) and not isinstance(v, bool),
    "integer": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "boolean": lambda v: isinstance(v, bool),
    "null": lambda v: v is None,
}


def _check_type(types, value) -> bool:
    if isinstance(types, str):
        types = [types]
    return any(_TYPE_CHECKS.get(t, lambda v: True)(value) for t in types)


def validate_schema(schema: dict, value: Any, path: str = "$") -> list[str]:
    """Validate ``value`` against the schema subset. Returns error strings
    (empty = valid). Unknown schema keywords are ignored, never fatal."""
    errors: list[str] = []
    types = schema.get("type")
    if types is not None and not _check_type(types, value):
        errors.append(f"{path}: expected {types}, got {type(value).__name__}")
        return errors  # type mismatch: deeper checks would be noise

    if "enum" in schema and value not in schema["enum"]:
        errors.append(f"{path}: {value!r} not in {schema['enum']}")

    if isinstance(value, (int, float)) and not isinstance(value, bool):
        if "minimum" in schema and value < schema["minimum"]:
            errors.append(f"{path}: {value} < minimum {schema['minimum']}")
        if "maximum" in schema and value > schema["maximum"]:
            errors.append(f"{path}: {value} > maximum {schema['maximum']}")

    if isinstance(value, dict):
        props = schema.get("properties", {})
        for name in schema.get("required", []):
            if name not in value:
                errors.append(f"{path}: missing required property {name!r}")
        for key, sub in value.items():
            if key in props:
                errors.extend(validate_schema(props[key], sub, f"{path}.{key}"))
            elif schema.get("additionalProperties") is False:
                errors.append(f"{path}: unknown property {key!r}")
            elif isinstance(schema.get("additionalProperties"), dict):
                errors.extend(validate_schema(schema["additionalProperties"], sub,
                                              f"{path}.{key}"))

    if isinstance(value, (list, tuple)) and isinstance(schema.get("items"), dict):
        for i, item in enumerate(value):
            errors.extend(validate_schema(schema["items"], item, f"{path}[{i}]"))

    return errors


@dataclass(frozen=True)
class PluginManifest:
    """What ``openclaw.plugin.json`` declares: identity + config schema."""

    id: str
    description: str
    version: str = "1.0.0"
    config_schema: dict = field(default_factory=dict)
    commands: tuple = ()          # chat commands the plugin registers
    gateway_methods: tuple = ()   # RPC methods the plugin registers
    hooks: tuple = ()             # hook names the plugin attaches to

    def validate_config(self, config: Optional[dict]) -> list[str]:
        if config is None or not self.config_schema:
            return []
        return validate_schema(self.config_schema, config)

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "version": self.version,
            "description": self.description,
            "configSchema": self.config_schema,
            "commands": list(self.commands),
            "gatewayMethods": list(self.gateway_methods),
            "hooks": list(self.hooks),
        }


def _bool(desc: str = "") -> dict:
    return {"type": "boolean", "description": desc} if desc else {"type": "boolean"}


def enabled_section(extra: Optional[dict] = None, **props) -> dict:
    """Common ``{enabled: bool, ...}`` sub-object schema."""
    merged = {"enabled": _bool()}
    merged.update(extra or {})
    merged.update(props)
    return {"type": "object", "properties": merged}

"""Batched LLM fact extraction (reference: knowledge-engine/src/
llm-enhancer.ts — batched messages → SPO facts tagged ``extracted-llm``)."""

from __future__ import annotations

from typing import Callable, Optional

from ..utils.llm_json import parse_llm_json

PROMPT = (
    "Extract factual subject-predicate-object triples from these messages. "
    "Only durable facts (preferences, relationships, attributes), no "
    "small talk. Respond ONLY JSON: "
    '{"facts": [{"subject": str, "predicate": str, "object": str}]}'
)


class KnowledgeLlmEnhancer:
    def __init__(self, call_llm: Callable[[str], str], logger, batch_size: int = 3):
        self.call_llm = call_llm
        self.logger = logger
        self.batch_size = batch_size
        self._batch: list[str] = []

    def add_to_batch(self, content: str) -> Optional[list[dict]]:
        self._batch.append(content[:2000])
        if len(self._batch) < self.batch_size:
            return None
        return self.send_batch()

    def send_batch(self) -> Optional[list[dict]]:
        if not self._batch:
            return None
        batch, self._batch = self._batch, []
        prompt = PROMPT + "\n\nMESSAGES:\n" + "\n".join(f"- {m}" for m in batch)
        try:
            raw = self.call_llm(prompt)
        except Exception as exc:  # noqa: BLE001 — silent fallback to regex-only
            self.logger.debug(f"knowledge LLM batch failed: {exc}")
            return None
        parsed = parse_llm_json(raw)
        if parsed is None:
            return None
        facts = []
        for f in parsed.get("facts", []):
            if isinstance(f, dict) and all(isinstance(f.get(k), str) and f.get(k)
                                           for k in ("subject", "predicate", "object")):
                facts.append({"subject": f["subject"], "predicate": f["predicate"],
                              "object": f["object"]})
        return facts or None

"""Fact store: subject–predicate–object triples with relevance lifecycle
(reference: knowledge-engine/src/fact-store.ts:11-264).

Content-dedupe boosts relevance on re-add; relevance decays on a
maintenance schedule; pruning drops the least relevant facts above the cap;
persistence is a debounced atomic write of ``facts.json``.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Optional

from ..storage.atomic import AtomicStorage

DEFAULT_STORE_CONFIG = {
    "maxFacts": 2000,
    "writeDebounceMs": 2000,
    "relevanceBoost": 0.2,
    "decayFactor": 0.95,
    "pruneBelowRelevance": 0.05,
}


@dataclass
class Fact:
    id: str
    subject: str
    predicate: str
    object: str
    source: str = "extracted-regex"
    created_at: str = ""
    last_accessed: str = ""
    relevance: float = 1.0

    def to_dict(self) -> dict:
        return {"id": self.id, "subject": self.subject, "predicate": self.predicate,
                "object": self.object, "source": self.source,
                "createdAt": self.created_at, "lastAccessed": self.last_accessed,
                "relevance": self.relevance}

    @classmethod
    def from_dict(cls, d: dict) -> "Fact":
        return cls(id=d.get("id") or str(uuid.uuid4()),
                   subject=d.get("subject", ""), predicate=d.get("predicate", ""),
                   object=d.get("object", ""), source=d.get("source", "unknown"),
                   created_at=d.get("createdAt", ""),
                   last_accessed=d.get("lastAccessed", ""),
                   relevance=float(d.get("relevance", 1.0)))


class FactStore:
    def __init__(self, workspace: str | Path, config: Optional[dict] = None,
                 logger=None, clock: Callable[[], float] = time.time,
                 wall_timers: bool = True):
        self.config = {**DEFAULT_STORE_CONFIG, **(config or {})}
        self.logger = logger
        self.clock = clock
        self.storage = AtomicStorage(Path(workspace) / "knowledge", wall=wall_timers)
        self.facts: dict[str, Fact] = {}
        self.loaded = False

    def _iso(self) -> str:
        t = time.gmtime(self.clock())
        return (f"{t.tm_year:04d}-{t.tm_mon:02d}-{t.tm_mday:02d}T"
                f"{t.tm_hour:02d}:{t.tm_min:02d}:{t.tm_sec:02d}Z")

    def load(self) -> None:
        if self.loaded:
            return
        data = self.storage.load("facts.json")
        if isinstance(data, dict) and isinstance(data.get("facts"), list):
            self.facts = {f["id"]: Fact.from_dict(f) for f in data["facts"] if f.get("id")}
            if self.logger:
                self.logger.info(f"Loaded {len(self.facts)} facts from storage")
        self.loaded = True

    def _commit(self) -> None:
        self.storage.save_debounced(
            "facts.json",
            lambda: {"version": 1, "updated": self._iso(),
                     "facts": [f.to_dict() for f in self.facts.values()]},
            delay_s=self.config["writeDebounceMs"] / 1000.0)

    def flush(self) -> None:
        if self.loaded:
            self.storage.flush_all()

    def add_fact(self, subject: str, predicate: str, object_: str,
                 source: str = "extracted-regex") -> Fact:
        if not self.loaded:
            raise RuntimeError("FactStore not loaded; call load() first")
        now = self._iso()
        for fact in self.facts.values():
            if (fact.subject == subject and fact.predicate == predicate
                    and fact.object == object_):
                fact.relevance = min(1.0, fact.relevance + self.config["relevanceBoost"])
                fact.last_accessed = now
                self._commit()
                return fact
        fact = Fact(id=str(uuid.uuid4()), subject=subject, predicate=predicate,
                    object=object_, source=source, created_at=now,
                    last_accessed=now, relevance=1.0)
        self.facts[fact.id] = fact
        self._prune()
        self._commit()
        return fact

    def query(self, subject: Optional[str] = None, predicate: Optional[str] = None,
              text: Optional[str] = None, limit: int = 50) -> list[Fact]:
        out = []
        needle = (text or "").lower()
        for fact in self.facts.values():
            if subject and fact.subject.lower() != subject.lower():
                continue
            if predicate and fact.predicate.lower() != predicate.lower():
                continue
            if needle and needle not in f"{fact.subject} {fact.predicate} {fact.object}".lower():
                continue
            out.append(fact)
        out.sort(key=lambda f: -f.relevance)
        return out[:limit]

    def decay_facts(self) -> int:
        """One decay tick: relevance *= decayFactor; prune below threshold."""
        factor = self.config["decayFactor"]
        threshold = self.config["pruneBelowRelevance"]
        dead = []
        for fact in self.facts.values():
            fact.relevance *= factor
            if fact.relevance < threshold:
                dead.append(fact.id)
        for fid in dead:
            del self.facts[fid]
        if dead or self.facts:
            self._commit()
        return len(dead)

    def _prune(self) -> None:
        cap = self.config["maxFacts"]
        if len(self.facts) <= cap:
            return
        ordered = sorted(self.facts.values(), key=lambda f: f.relevance)
        for fact in ordered[: len(self.facts) - cap]:
            del self.facts[fact.id]

    def count(self) -> int:
        return len(self.facts)

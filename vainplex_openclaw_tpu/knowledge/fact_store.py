"""Fact store: subject–predicate–object triples with relevance lifecycle
(reference: knowledge-engine/src/fact-store.ts:11-264).

Content-dedupe boosts relevance on re-add; relevance decays on a
maintenance schedule; pruning drops the least relevant facts above the cap;
persistence is a debounced atomic write of ``facts.json``.

Serve-scale ingest (ISSUE 2): ``add_fact`` dedupes through a
``(subject, predicate, object)`` index kept in lockstep with ``self.facts``
— O(1) per add instead of a linear scan over the whole store, which at the
2000-fact cap made every insert an O(n) pass (O(n²) to fill the store).
The scan survives as ``find_by_content_scan``, the equivalence oracle the
property tests replay against the index. ``query`` reads a cached lowercase
haystack per fact instead of re-lowercasing three fields per fact per call.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Optional

from ..storage.atomic import AtomicStorage
from ..utils.ids import prng_uuid4
from ..utils.stage_timer import StageTimer

DEFAULT_STORE_CONFIG = {
    "maxFacts": 2000,
    "writeDebounceMs": 2000,
    "relevanceBoost": 0.2,
    "decayFactor": 0.95,
    "pruneBelowRelevance": 0.05,
}

# uuid4() pays a urandom syscall per call — half the ingest budget at the
# 2000-fact cap once dedupe is O(1). Fact ids are storage keys, not security
# tokens, so the shared process-seeded PRNG (utils/ids.py: same 122 random
# bits and RFC-4122 text shape, urandom-seeded, reseeded after fork) keeps
# the collision math while staying in userspace.
_new_fact_id = prng_uuid4


@dataclass
class Fact:
    id: str
    subject: str
    predicate: str
    object: str
    source: str = "extracted-regex"
    created_at: str = ""
    last_accessed: str = ""
    relevance: float = 1.0

    def to_dict(self) -> dict:
        return {"id": self.id, "subject": self.subject, "predicate": self.predicate,
                "object": self.object, "source": self.source,
                "createdAt": self.created_at, "lastAccessed": self.last_accessed,
                "relevance": self.relevance}

    @classmethod
    def from_dict(cls, d: dict) -> "Fact":
        return cls(id=d.get("id") or str(uuid.uuid4()),
                   subject=d.get("subject", ""), predicate=d.get("predicate", ""),
                   object=d.get("object", ""), source=d.get("source", "unknown"),
                   created_at=d.get("createdAt", ""),
                   last_accessed=d.get("lastAccessed", ""),
                   relevance=float(d.get("relevance", 1.0)))

    def content_key(self) -> tuple[str, str, str]:
        return (self.subject, self.predicate, self.object)


class FactStore:
    def __init__(self, workspace: str | Path, config: Optional[dict] = None,
                 logger=None, clock: Callable[[], float] = time.time,
                 wall_timers: bool = True, timer: Optional[StageTimer] = None,
                 journal=None):
        self.config = {**DEFAULT_STORE_CONFIG, **(config or {})}
        self.logger = logger
        self.clock = clock
        self.timer = timer if timer is not None else StageTimer()
        # Shared workspace journal (ISSUE 7): the debounced facts.json save
        # becomes a group-committed wal append; None keeps the legacy
        # atomic-rename path (the storage.journal:false escape hatch).
        self.storage = AtomicStorage(Path(workspace) / "knowledge", wall=wall_timers,
                                     journal=journal, stream_prefix="knowledge")
        # Maintenance decay runs on a daemon thread while the gateway thread
        # ingests: iteration over self.facts and the index bookkeeping must
        # not interleave (RLock: add_fact's prune path re-enters).
        self._facts_lock = threading.RLock()
        self.facts: dict[str, Fact] = {}
        # (subject, predicate, object) → fact id, in lockstep with self.facts.
        # Dedupe semantics are exact-match on the raw fields, same as the scan.
        self._content_index: dict[tuple[str, str, str], str] = {}
        # fact id → (subject_lower, predicate_lower, "s p o" haystack_lower);
        # fields are immutable after creation, so the cache never goes stale.
        self._lower: dict[str, tuple[str, str, str]] = {}
        self._iso_cache: tuple[int, str] = (-1, "")
        self.loaded = False

    def _iso(self) -> str:
        # Second-resolution timestamps: cache per whole second so ingest
        # bursts don't pay gmtime + formatting per fact.
        now = int(self.clock())
        if self._iso_cache[0] != now:
            t = time.gmtime(now)
            self._iso_cache = (now, f"{t.tm_year:04d}-{t.tm_mon:02d}-{t.tm_mday:02d}T"
                                    f"{t.tm_hour:02d}:{t.tm_min:02d}:{t.tm_sec:02d}Z")
        return self._iso_cache[1]

    def load(self) -> None:
        with self._facts_lock:
            if self.loaded:
                return
            data = self.storage.load("facts.json")
            if isinstance(data, dict) and isinstance(data.get("facts"), list):
                self.facts = {f["id"]: Fact.from_dict(f) for f in data["facts"]
                              if f.get("id")}
                for fact in self.facts.values():
                    self._index(fact)
                if self.logger:
                    self.logger.info(f"Loaded {len(self.facts)} facts from storage")
            self.loaded = True

    def _snapshot_payload(self) -> dict:
        # The debounced supplier runs on the Debouncer's TIMER thread (or
        # the atexit flush), not on the thread that called _commit — an
        # unlocked iteration here races add/decay/prune mutating the dict
        # mid-serialize ("dict changed size during iteration", or a
        # torn fact list). Found by graftlint's deferred-closure rule
        # (GL-LOCK-GUARD, ISSUE 8); the RLock makes the synchronous
        # flush-under-lock path re-entrant and safe.
        with self._facts_lock:
            return {"version": 1, "updated": self._iso(),
                    "facts": [f.to_dict() for f in self.facts.values()]}

    def _commit(self) -> None:
        self.storage.save_debounced(
            "facts.json", self._snapshot_payload,
            delay_s=self.config["writeDebounceMs"] / 1000.0)

    def flush(self) -> None:
        if self.loaded:
            self.storage.flush_all()

    def hibernate(self) -> None:
        """Evict the store down to its journaled snapshot (ISSUE 11): flush
        the debounced save (journal mode compacts ``facts.json`` current),
        then drop the in-RAM facts dict and both indexes. The next
        ``load()`` faults everything back in from the snapshot — the wake
        path is the ordinary load path.

        The WHOLE evict runs under ``_facts_lock`` (flush included): a
        racing ``load()``/``add_fact()`` must serialize either entirely
        before (its fact is flushed with the rest) or entirely after (it
        reloads the flushed snapshot, or raises the ordinary not-loaded
        error into the fail-open hook). Releasing the lock mid-evict would
        let a reload slip between the flush and the clear — a
        loaded-but-empty store whose next debounced save persists empty.
        Hibernation is an idle-path event, so blocking under the hot lock
        here is cold by construction (``allow_blocking`` in the GUARDED
        table, same rationale as ``load``). The flush's debounced supplier
        re-enters the RLock on this thread; the Debouncer calls it with no
        Debouncer lock held, so there is no lock-order edge."""
        with self._facts_lock:
            if not self.loaded:
                return
            self.storage.flush_all()
            self.facts.clear()
            self._content_index.clear()
            self._lower.clear()
            self.loaded = False

    # ── content index ────────────────────────────────────────────────

    def _index(self, fact: Fact) -> None:
        # setdefault, not assignment: on the paths where duplicate content
        # keys are possible (loading a pre-index facts.json, or a fact
        # inserted behind the store's back), the index must resolve to the
        # FIRST fact in iteration order — exactly what the linear-scan
        # oracle (find_by_content_scan) returns.
        self._content_index.setdefault(fact.content_key(), fact.id)
        self._lower[fact.id] = (
            fact.subject.lower(), fact.predicate.lower(),
            f"{fact.subject} {fact.predicate} {fact.object}".lower())

    def _unindex(self, fact: Fact) -> None:
        key = fact.content_key()
        if self._content_index.get(key) == fact.id:
            del self._content_index[key]
            # Duplicate content keys exist only when facts landed behind the
            # store's back (or a pre-index file held them) — detectable in
            # O(1): every distinctly-keyed indexed fact contributes one index
            # entry, so fewer entries than facts means a shadowed duplicate
            # may survive this removal and must inherit the key, or the index
            # would diverge from the linear-scan oracle. Normal operation
            # never enters the scan.
            if len(self._content_index) + 1 < len(self.facts):
                for other in self.facts.values():
                    if other.id != fact.id and other.content_key() == key:
                        self._content_index[key] = other.id
                        break
        self._lower.pop(fact.id, None)

    def find_by_content_scan(self, subject: str, predicate: str,
                             object_: str) -> Optional[Fact]:
        """The pre-index O(n) dedupe scan, kept as the equivalence oracle:
        property tests replay randomized add/decay/prune sequences and pin
        that the index finds exactly what this scan finds."""
        with self._facts_lock:
            for fact in self.facts.values():
                if (fact.subject == subject and fact.predicate == predicate
                        and fact.object == object_):
                    return fact
            return None

    def add_fact(self, subject: str, predicate: str, object_: str,
                 source: str = "extracted-regex") -> Fact:
        if not self.loaded:
            raise RuntimeError("FactStore not loaded; call load() first")
        with self.timer.stage("ingest"), self._facts_lock:
            now = self._iso()
            existing_id = self._content_index.get((subject, predicate, object_))
            if existing_id is not None:
                fact = self.facts[existing_id]
                fact.relevance = min(1.0, fact.relevance + self.config["relevanceBoost"])
                fact.last_accessed = now
                self._commit()
                return fact
            fact = Fact(id=_new_fact_id(), subject=subject, predicate=predicate,
                        object=object_, source=source, created_at=now,
                        last_accessed=now, relevance=1.0)
            self.facts[fact.id] = fact
            self._index(fact)
            self._prune()
            self._commit()
            return fact

    def query(self, subject: Optional[str] = None, predicate: Optional[str] = None,
              text: Optional[str] = None, limit: int = 50) -> list[Fact]:
        with self.timer.stage("query"), self._facts_lock:
            out = []
            needle = (text or "").lower()
            subject_l = subject.lower() if subject else None
            predicate_l = predicate.lower() if predicate else None
            for fact in self.facts.values():
                cached = self._lower.get(fact.id)
                if cached is None:  # fact inserted behind the store's back
                    self._index(fact)
                    cached = self._lower[fact.id]
                sub_l, pred_l, haystack = cached
                if subject_l and sub_l != subject_l:
                    continue
                if predicate_l and pred_l != predicate_l:
                    continue
                if needle and needle not in haystack:
                    continue
                out.append(fact)
            # Deterministic under relevance ties (created_at, then id) so the
            # limit truncation below is stable run to run.
            out.sort(key=lambda f: (-f.relevance, f.created_at, f.id))
            return out[:limit]

    def decay_facts(self) -> int:
        """One decay tick: relevance *= decayFactor; prune below threshold.

        Skips the full-store serialization when the tick was an empty delta —
        nothing decayed (empty store, or decayFactor 1.0) and nothing pruned."""
        factor = self.config["decayFactor"]
        threshold = self.config["pruneBelowRelevance"]
        with self._facts_lock:
            dead = []
            for fact in self.facts.values():
                fact.relevance *= factor
                if fact.relevance < threshold:
                    dead.append(fact.id)
            for fid in dead:
                self._unindex(self.facts[fid])
                del self.facts[fid]
            if dead or (self.facts and factor != 1.0):
                self._commit()
            return len(dead)

    def _prune(self) -> None:
        cap = self.config["maxFacts"]
        if len(self.facts) <= cap:
            return
        ordered = sorted(self.facts.values(), key=lambda f: f.relevance)
        for fact in ordered[: len(self.facts) - cap]:
            self._unindex(fact)
            del self.facts[fact.id]

    def snapshot(self) -> list[Fact]:
        """Locked point-in-time list of live facts — what maintenance ticks
        iterate instead of the live dict, which the gateway thread mutates
        mid-iteration otherwise."""
        with self._facts_lock:
            return list(self.facts.values())

    def count(self) -> int:
        return len(self.facts)

"""Regex NER (reference: knowledge-engine/src/entity-extractor.ts,
patterns.ts).

Patterns: email, url, ISO/common/German/English dates, proper nouns (with a
sentence-start exclusion list), product names (versions/Roman numerals/
camelCase), organization suffixes. Canonicalization strips org suffixes and
trailing punctuation; repeated mentions merge and bump counts. Python's
``re`` is stateless so the reference's fresh-RegExp-per-access Proxy (its
/g lastIndex fix) has no equivalent hazard here — patterns compile once.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field
from typing import Callable

EXCLUDED_WORDS = (
    "A", "An", "The", "Hello", "My", "This", "Contact", "He", "She", "It",
    "We", "They", "I", "You", "His", "Her", "Our", "Your", "Their", "Its",
    "That", "These", "Those", "What", "Which", "Who", "How", "When", "Where",
    "Why", "But", "And", "Or", "So", "Not", "No", "Yes", "Also", "Just",
    "For", "From", "With", "About", "After", "Before", "Between", "During",
    "Into", "Through", "Event", "Talk", "Project", "Multiple", "German",
    "Am", "Are", "Is", "Was", "Were", "Has", "Have", "Had", "Do", "Does",
    "Did", "Will", "Would", "Could", "Should", "May", "Might", "Must",
    "Can", "Shall", "If", "Then",
)

_EXCL = "|".join(f"{w}\\b" for w in EXCLUDED_WORDS)
_CAP = r"(?:[A-Z][a-z']*(?:[A-Z][a-z']+)*|[A-Z]{2,})"
_DE_MONTHS = ("Januar|Februar|März|April|Mai|Juni|Juli|August|September|"
              "Oktober|November|Dezember")
_EN_MONTHS = ("January|February|March|April|May|June|July|August|September|"
              "October|November|December")

PATTERNS: dict[str, re.Pattern] = {
    "email": re.compile(r"\b[a-zA-Z0-9._%+-]+@[a-zA-Z0-9.-]+\.[a-zA-Z]{2,}\b"),
    "url": re.compile(r"\bhttps?://[^\s/$.?#].[^\s]*"),
    "iso_date": re.compile(r"\b\d{4}-\d{2}-\d{2}(?:T\d{2}:\d{2}:\d{2}(?:\.\d+)?Z?)?\b"),
    "common_date": re.compile(r"\b(?:\d{1,2}/\d{1,2}/\d{2,4})|(?:\d{1,2}\.\d{1,2}\.\d{2,4})\b"),
    "german_date": re.compile(rf"\b\d{{1,2}}\.\s(?:{_DE_MONTHS})\s+\d{{4}}\b", re.IGNORECASE),
    "english_date": re.compile(rf"\b(?:{_EN_MONTHS})\s+\d{{1,2}}(?:st|nd|rd|th)?,\s+\d{{4}}\b",
                               re.IGNORECASE),
    "proper_noun": re.compile(rf"\b(?!{_EXCL}){_CAP}(?:(?:-|\s)(?!{_EXCL}){_CAP})*\b"),
    "product_name": re.compile(
        rf"\b(?:(?!{_EXCL})[A-Z][a-zA-Z0-9]{{2,}}(?:\s[a-zA-Z]+)*\s[IVXLCDM]+"
        r"|[a-zA-Z][a-zA-Z0-9-]{2,}[\s-]v?\d+(?:\.\d+)?"
        r"|[a-zA-Z][a-zA-Z0-9]+[IVXLCDM]+)\b"),
    "organization_suffix": re.compile(
        r"\b(?:[A-Z][A-Za-z0-9]+(?:\s[A-Z][A-Za-z0-9]+)*),?\s?"
        r"(?:Inc\.|LLC|Corp\.|GmbH|AG|Ltd\.)"),
}

PATTERN_TYPE_MAP = {
    "email": "email", "url": "url",
    "iso_date": "date", "common_date": "date", "german_date": "date",
    "english_date": "date",
    "proper_noun": "unknown", "product_name": "product",
    "organization_suffix": "organization",
}

_ORG_SUFFIX_RE = re.compile(r",?\s?(?:Inc\.|LLC|Corp\.|GmbH|AG|Ltd\.)$", re.IGNORECASE)
_TRAILING_PUNCT_RE = re.compile(r"[.,!?;:]$")

TYPE_IMPORTANCE = {"email": 0.8, "organization": 0.8, "product": 0.7,
                   "url": 0.6, "date": 0.5, "unknown": 0.4}


@dataclass
class Entity:
    id: str
    type: str
    value: str
    mentions: list[str] = field(default_factory=list)
    count: int = 1
    importance: float = 0.4
    last_seen: str = ""
    source: list[str] = field(default_factory=lambda: ["regex"])

    def to_dict(self) -> dict:
        return {"id": self.id, "type": self.type, "value": self.value,
                "mentions": self.mentions, "count": self.count,
                "importance": self.importance, "lastSeen": self.last_seen,
                "source": self.source}


def canonicalize(value: str, entity_type: str) -> str:
    if entity_type == "organization":
        return _ORG_SUFFIX_RE.sub("", value).strip()
    return _TRAILING_PUNCT_RE.sub("", value).strip()


def initial_importance(entity_type: str, value: str) -> float:
    base = TYPE_IMPORTANCE.get(entity_type, 0.4)
    if len(value) > 20:
        base = min(1.0, base + 0.1)  # longer names are more specific
    return base


class EntityExtractor:
    def __init__(self, logger=None, clock: Callable[[], float] = time.time):
        self.logger = logger
        self.clock = clock

    def extract(self, text: str) -> list[Entity]:
        found: dict[str, Entity] = {}
        for key, pattern in PATTERNS.items():
            entity_type = PATTERN_TYPE_MAP.get(key, "unknown")
            for m in pattern.finditer(text):
                value = m.group(0).strip()
                if value:
                    self._process(value, entity_type, found)
        return list(found.values())

    def _process(self, value: str, entity_type: str, found: dict) -> None:
        canonical = canonicalize(value, entity_type)
        if not canonical:
            return
        slug = re.sub(r"\s+", "-", canonical.lower())
        entity_id = f"{entity_type}:{slug}"
        existing = found.get(entity_id)
        if existing is not None:
            if value not in existing.mentions:
                existing.mentions.append(value)
            existing.count += 1
            return
        t = time.gmtime(self.clock())
        found[entity_id] = Entity(
            id=entity_id, type=entity_type, value=canonical, mentions=[value],
            importance=initial_importance(entity_type, canonical),
            last_seen=(f"{t.tm_year:04d}-{t.tm_mon:02d}-{t.tm_mday:02d}T"
                       f"{t.tm_hour:02d}:{t.tm_min:02d}:{t.tm_sec:02d}Z"),
        )

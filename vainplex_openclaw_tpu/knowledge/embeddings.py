"""Embeddings sync (reference: knowledge-engine/src/embeddings.ts:6-82).

Two backends:
- ``chroma``: the reference behavior — facts become
  ``"subject predicate object."`` documents POSTed to a ChromaDB-v2-shaped
  endpoint (``{name}`` substituted, string-only metadata), via a DI'd
  ``http_post``.
- ``local``: the TPU-native path — the CortexEncoder embeds the documents
  on-device into an in-memory matrix with cosine top-k search; no HTTP, no
  external vector DB. This is the default in zero-egress environments.
"""

from __future__ import annotations

import json
from typing import Callable, Optional

import numpy as np


def _default_http_post(url: str, payload: dict, timeout: float = 15.0) -> dict:
    from urllib.request import Request, urlopen

    req = Request(url, data=json.dumps(payload).encode(),
                  headers={"Content-Type": "application/json"})
    with urlopen(req, timeout=timeout) as resp:  # noqa: S310 — operator-configured endpoint
        body = resp.read().decode()
        return json.loads(body) if body else {}


def fact_document(fact) -> str:
    return f"{fact.subject} {fact.predicate.replace('-', ' ')} {fact.object}."


def construct_chroma_payload(facts: list) -> dict:
    payload = {"ids": [], "documents": [], "metadatas": []}
    for fact in facts:
        payload["ids"].append(fact.id)
        payload["documents"].append(fact_document(fact))
        payload["metadatas"].append({  # v2 requires string-only metadata
            "subject": fact.subject, "predicate": fact.predicate,
            "object": fact.object, "source": fact.source,
            "createdAt": fact.created_at,
        })
    return payload


class ChromaEmbeddings:
    def __init__(self, config: dict, logger, http_post: Callable = _default_http_post):
        self.config = config
        self.logger = logger
        self.http_post = http_post

    def enabled(self) -> bool:
        return bool(self.config.get("enabled"))

    def _endpoint(self) -> str:
        url = (self.config.get("endpoint") or "").replace(
            "{name}", self.config.get("collectionName", "facts"))
        import re

        return re.sub(r"([^:])//", r"\1/", url)

    def sync(self, facts: list) -> int:
        if not self.enabled() or not facts:
            return 0
        try:
            self.http_post(self._endpoint(), construct_chroma_payload(facts))
            self.logger.info(f"Synced {len(facts)} facts to ChromaDB")
            return len(facts)
        except Exception as exc:  # noqa: BLE001 — embeddings are best-effort
            self.logger.error(f"Embeddings sync failed: {exc}")
            return 0

    def remove(self, ids) -> int:
        """Best-effort delete of pruned facts from the collection (Chroma v2
        sibling ``…/delete`` endpoint of the configured upsert URL).

        Returns the number of ids *settled* — deleted, or permanently
        undeletable (custom endpoint we cannot derive a delete URL from).
        Transient failures return fewer than ``len(ids)`` so the caller
        retries only those next tick."""
        ids = sorted(ids)
        if not self.enabled() or not ids:
            return 0
        endpoint = self._endpoint()
        if not endpoint.endswith("/upsert"):
            # Permanent: no retry will ever succeed — warn once, settle.
            self.logger.warn(
                "cannot derive delete endpoint from custom upsert URL; "
                f"{len(ids)} pruned facts remain in ChromaDB")
            return len(ids)
        try:
            self.http_post(endpoint[: -len("/upsert")] + "/delete", {"ids": ids})
            self.logger.info(f"Removed {len(ids)} pruned facts from ChromaDB")
            return len(ids)
        except Exception as exc:  # noqa: BLE001 — embeddings are best-effort
            self.logger.error(f"Embeddings delete failed: {exc}")
            return 0


class LocalEmbeddings:
    """On-device fact embeddings: CortexEncoder vector ⊕ hashed bag-of-tokens,
    cosine top-k by one matmul. The learned half runs the SHIPPED trained
    checkpoint (models/pretrained.py, VERDICT r3 #2) so label-semantic
    neighborhoods (failure-ish facts near failure-ish queries) come for free;
    the bag-of-tokens half guarantees lexical grounding. Falls back to
    random init only when no checkpoint is present. Lazy model init (first
    sync pays compile/restore)."""

    def __init__(self, logger, seed: int = 11, learned_weight: float = 0.5,
                 checkpoint_dir: Optional[str] = None):
        self.logger = logger
        self.seed = seed
        self.learned_weight = learned_weight
        self.checkpoint_dir = checkpoint_dir
        self._model = None
        self._ids: list[str] = []
        self._vectors: Optional[np.ndarray] = None
        self._docs: dict[str, str] = {}

    def enabled(self) -> bool:
        return True

    def _embed(self, texts: list[str]) -> np.ndarray:
        if self._model is None:
            from ..models.pretrained import load_pretrained

            self._model = load_pretrained(self.checkpoint_dir)
        if self._model is None:  # no shipped checkpoint anywhere
            import jax

            from ..models import EncoderConfig, cast_params, init_params

            cfg = EncoderConfig()
            self._model = (cfg, cast_params(init_params(jax.random.PRNGKey(self.seed), cfg),
                                            cfg.dtype))
        cfg, params = self._model
        from ..models import encode_texts, forward

        tokens = encode_texts(texts, cfg.seq_len, cfg.vocab_size)
        out = forward(params, tokens, cfg)
        learned = np.asarray(out["embedding"], dtype=np.float32)  # already L2-normed

        bow = np.zeros((len(texts), cfg.vocab_size), dtype=np.float32)
        for i, row in enumerate(tokens):
            ids = row[row > 1]  # drop PAD/CLS
            np.add.at(bow[i], ids, 1.0)
        norms = np.linalg.norm(bow, axis=1, keepdims=True)
        bow = np.where(norms > 0, bow / np.maximum(norms, 1e-9), bow)

        w = self.learned_weight
        return np.concatenate([learned * np.sqrt(w), bow * np.sqrt(1.0 - w)], axis=1)

    def sync(self, facts: list) -> int:
        if not facts:
            return 0
        docs = [fact_document(f) for f in facts]
        vectors = self._embed(docs)
        for fact, doc in zip(facts, docs):
            self._docs[fact.id] = doc
        new_ids = [f.id for f in facts]
        if self._vectors is None:
            self._ids, self._vectors = new_ids, vectors
        else:
            keep = [i for i, fid in enumerate(self._ids) if fid not in set(new_ids)]
            self._ids = [self._ids[i] for i in keep] + new_ids
            self._vectors = np.concatenate([self._vectors[keep], vectors]) \
                if keep else vectors
        return len(facts)

    def search(self, query: str, k: int = 5) -> list[dict]:
        if self._vectors is None or not self._ids:
            return []
        q = self._embed([query])[0]
        scores = self._vectors @ q
        order = np.argsort(-scores)[:k]
        return [{"id": self._ids[i], "document": self._docs.get(self._ids[i], ""),
                 "score": float(scores[i])} for i in order]

    def remove(self, ids) -> int:
        """Drop pruned facts from the index so search never returns them.
        Ids already absent count as settled (the desired state holds)."""
        dead = set(ids)
        if not dead:
            return 0
        if self._vectors is None:
            return len(dead)
        keep = [i for i, fid in enumerate(self._ids) if fid not in dead]
        if len(keep) < len(self._ids):
            self._ids = [self._ids[i] for i in keep]
            self._vectors = self._vectors[keep] if keep else None
        for fid in dead:
            self._docs.pop(fid, None)
        return len(dead)

    def count(self) -> int:
        return len(self._ids)


def create_embeddings(config: dict, logger, http_post: Callable = _default_http_post):
    backend = (config or {}).get("backend", "local")
    if backend == "chroma":
        return ChromaEmbeddings(config, logger, http_post)
    if backend == "local":
        return LocalEmbeddings(logger,
                               checkpoint_dir=(config or {}).get("checkpointDir"))
    return None

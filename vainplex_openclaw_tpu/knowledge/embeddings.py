"""Embeddings sync (reference: knowledge-engine/src/embeddings.ts:6-82).

Two backends:
- ``chroma``: the reference behavior — facts become
  ``"subject predicate object."`` documents POSTed to a ChromaDB-v2-shaped
  endpoint (``{name}`` substituted, string-only metadata), via a DI'd
  ``http_post``.
- ``local``: the TPU-native path — the CortexEncoder embeds the documents
  on-device into an in-memory matrix with cosine top-k search; no HTTP, no
  external vector DB. This is the default in zero-egress environments.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from ..ops.similarity import pad_rows, pow2_bucket
from ..utils.stage_timer import StageTimer


def _default_http_post(url: str, payload: dict, timeout: float = 15.0) -> dict:
    from urllib.request import Request, urlopen

    req = Request(url, data=json.dumps(payload).encode(),
                  headers={"Content-Type": "application/json"})
    with urlopen(req, timeout=timeout) as resp:  # noqa: S310 — operator-configured endpoint
        body = resp.read().decode()
        return json.loads(body) if body else {}


def fact_document(fact) -> str:
    return f"{fact.subject} {fact.predicate.replace('-', ' ')} {fact.object}."


def construct_chroma_payload(facts: list) -> dict:
    payload = {"ids": [], "documents": [], "metadatas": []}
    for fact in facts:
        payload["ids"].append(fact.id)
        payload["documents"].append(fact_document(fact))
        payload["metadatas"].append({  # v2 requires string-only metadata
            "subject": fact.subject, "predicate": fact.predicate,
            "object": fact.object, "source": fact.source,
            "createdAt": fact.created_at,
        })
    return payload


class ChromaEmbeddings:
    def __init__(self, config: dict, logger, http_post: Callable = _default_http_post):
        self.config = config
        self.logger = logger
        self.http_post = http_post

    def enabled(self) -> bool:
        return bool(self.config.get("enabled"))

    def _endpoint(self) -> str:
        url = (self.config.get("endpoint") or "").replace(
            "{name}", self.config.get("collectionName", "facts"))
        import re

        return re.sub(r"([^:])//", r"\1/", url)

    def sync(self, facts: list) -> int:
        if not self.enabled() or not facts:
            return 0
        try:
            self.http_post(self._endpoint(), construct_chroma_payload(facts))
            self.logger.info(f"Synced {len(facts)} facts to ChromaDB")
            return len(facts)
        except Exception as exc:  # noqa: BLE001 — embeddings are best-effort
            self.logger.error(f"Embeddings sync failed: {exc}")
            return 0

    def remove(self, ids) -> int:
        """Best-effort delete of pruned facts from the collection (Chroma v2
        sibling ``…/delete`` endpoint of the configured upsert URL).

        Returns the number of ids *settled* — deleted, or permanently
        undeletable (custom endpoint we cannot derive a delete URL from).
        Transient failures return fewer than ``len(ids)`` so the caller
        retries only those next tick."""
        ids = sorted(ids)
        if not self.enabled() or not ids:
            return 0
        endpoint = self._endpoint()
        if not endpoint.endswith("/upsert"):
            # Permanent: no retry will ever succeed — warn once, settle.
            self.logger.warn(
                "cannot derive delete endpoint from custom upsert URL; "
                f"{len(ids)} pruned facts remain in ChromaDB")
            return len(ids)
        try:
            self.http_post(endpoint[: -len("/upsert")] + "/delete", {"ids": ids})
            self.logger.info(f"Removed {len(ids)} pruned facts from ChromaDB")
            return len(ids)
        except Exception as exc:  # noqa: BLE001 — embeddings are best-effort
            self.logger.error(f"Embeddings delete failed: {exc}")
            return 0


class LocalEmbeddings:
    """On-device fact embeddings: CortexEncoder vector ⊕ hashed bag-of-tokens,
    cosine top-k by one matmul. The learned half runs the SHIPPED trained
    checkpoint (models/pretrained.py, VERDICT r3 #2) so label-semantic
    neighborhoods (failure-ish facts near failure-ish queries) come for free;
    the bag-of-tokens half guarantees lexical grounding. Falls back to
    random init only when no checkpoint is present. Lazy model init (first
    sync pays compile/restore).

    Serve-scale layout (ISSUE 2):

    - ``_embed`` runs a jitted forward whose batch dim is bucketed to powers
      of two (``ops/similarity.pow2_bucket`` — the PR 1 shape policy), so
      sync batches and the single-query path share O(log N) compiled shapes
      instead of one XLA compile per distinct batch size. ``trace_count``
      bumps at trace time so tests can pin the cache behavior. The
      bag-of-tokens half is one vectorized flat scatter-add instead of a
      per-row Python loop.
    - Vectors live in a capacity-doubling float32 arena; ``sync`` overwrites
      re-synced ids in place and appends new ids, ``remove`` compacts by
      swapping the last row in (no tombstones). The pre-arena full
      ``np.concatenate`` rebuild stays the equivalence oracle in
      tests/test_knowledge_perf_equiv.py: per-id stored vectors are pinned
      BITWISE; scores agree to BLAS layout rounding (sgemv is row-position
      sensitive at 1 ulp — true of the pre-arena layout too).
    - ``search`` selects top-k via ``np.argpartition`` (O(n) instead of a
      full sort) and orders ties deterministically by (-score, id) — which
      also makes results independent of internal arena row order.
    - Query embeddings go through an LRU cache; entries are embeddings only
      (never result lists), so a cached query always scores against the
      CURRENT arena — a sync never serves stale search results.
    """

    def __init__(self, logger, seed: int = 11, learned_weight: float = 0.5,
                 checkpoint_dir: Optional[str] = None,
                 timer: Optional[StageTimer] = None,
                 query_cache_size: int = 256, mesh=None,
                 plan_family: str = "embeddings_forward"):
        self.logger = logger
        self.seed = seed
        self.learned_weight = learned_weight
        self.checkpoint_dir = checkpoint_dir
        self.timer = timer if timer is not None else StageTimer()
        self._model = None
        self._forward_jit = None
        self.trace_count = 0  # bumped at jit-trace time: once per bucket shape
        # Mesh serving (ISSUE 15): a jax Mesh (axes ("dp",)) routes _embed
        # through the data-parallel "embeddings_forward" sharding plan
        # (parallel/plan.py — replicated weights, batch over dp) and arena
        # search through a dp-sharded score matmul. None keeps the
        # single-device path verbatim — the equivalence oracle.
        # ``plan_family`` (ISSUE 18) selects the serving family — the
        # expert-parallel "embeddings_forward_moe" over (dp, ep) for MoE
        # checkpoints; the default stays the dp-only plan.
        self._mesh = mesh
        self._plan_family = plan_family
        # Device-committed arena copy for mesh search: re-committed (and
        # "shard"-attributed in the timer) only after host mutations —
        # sync/remove flip the dirty flag under the lock.
        self._device_arena = None
        self._device_arena_rows = 0
        self._arena_dirty = True
        # Maintenance syncs/removes run on a daemon thread while the serve
        # thread searches; in-place arena mutation (row overwrite, swap
        # compaction) would tear a concurrent matmul's view, so arena and
        # query-cache access is serialized. Embedding compute (the slow
        # part) stays outside the lock.
        self._lock = threading.Lock()
        # Separate init lock: first sync (maintenance thread) and first
        # search (serve thread) race the lazy model restore + jit wrapper
        # creation; double restore would double startup latency and break
        # the trace_count "once per compiled shape" invariant.
        self._init_lock = threading.Lock()
        self._docs: dict[str, str] = {}
        # Arena: rows [0, _size) of _arena are live; _ids[row] ↔ _pos[id].
        self._arena: Optional[np.ndarray] = None
        self._size = 0
        self._ids: list[str] = []
        self._pos: dict[str, int] = {}
        self._query_cache: "OrderedDict[str, np.ndarray]" = OrderedDict()
        self._query_cache_size = query_cache_size
        self.query_cache_hits = 0
        self.query_cache_misses = 0

    def enabled(self) -> bool:
        return True

    # ── embedding ────────────────────────────────────────────────────

    def _ensure_model(self):
        with self._init_lock:
            if self._model is None:
                from ..models.pretrained import load_pretrained

                self._model = load_pretrained(self.checkpoint_dir)
            if self._model is None:  # no shipped checkpoint anywhere
                import jax

                from ..models import EncoderConfig, cast_params, init_params

                cfg = EncoderConfig()
                self._model = (cfg,
                               cast_params(init_params(jax.random.PRNGKey(self.seed), cfg),
                                           cfg.dtype))
            if self._forward_jit is None:
                import jax

                from ..models import forward

                cfg = self._model[0]

                def run(params, tokens):
                    self.trace_count += 1  # trace time: once per compiled shape
                    return forward(params, tokens, cfg)["embedding"]

                self._forward_jit = jax.jit(run)
            return self._model

    def _embed(self, texts: list[str]) -> np.ndarray:
        cfg, params = self._ensure_model()
        from ..models import encode_texts

        n = len(texts)
        tokens = encode_texts(texts, cfg.seq_len, cfg.vocab_size)
        # Bucket the batch dim to a power of two: zero-token padding rows are
        # batch-independent in the encoder (masked pooling clamps the
        # denominator) and are sliced back out, so the jit cache holds
        # O(log N) shapes instead of one compile per distinct batch size.
        if self._mesh is not None:
            # Data-parallel mesh forward (ISSUE 15): bucket floored at dp
            # (and the searched plan's bucket_min, ISSUE 16) so every
            # shard holds ≥1 row; weights replicated per the
            # embeddings_forward plan, N/dp rows per chip on full-store
            # syncs. Tolerance vs the single-device oracle is documented
            # in docs/tpu-numerics.md.
            from ..parallel import plan as sharding_plan

            padded = pad_rows(tokens, sharding_plan.serve_bucket(
                n, self._mesh, plan=self._plan_family))
            placed = sharding_plan.sharded_params(
                (self.checkpoint_dir or "shipped-default", self.seed),
                params, self._mesh, self._plan_family)
            tokens_dev = sharding_plan.place_tokens(
                padded, self._mesh, self._plan_family)
            out = sharding_plan.serve_forward(
                placed, tokens_dev, cfg, self._mesh, self._plan_family)
            learned = np.asarray(out["embedding"],
                                 dtype=np.float32)[:n]  # already L2-normed
        else:
            padded = pad_rows(tokens, pow2_bucket(n))
            learned = np.asarray(self._forward_jit(params, padded),
                                 dtype=np.float32)[:n]  # already L2-normed

        # Vectorized bag-of-tokens: one flat scatter-add over (row, token)
        # pair indices instead of a per-row Python loop — and not bincount,
        # whose int64 output would triple transient memory on a full-store
        # sync (the flat float32 buffer IS the bow matrix).
        mask = tokens > 1  # drop PAD/CLS
        rows = np.nonzero(mask)[0]
        ids = tokens[mask].astype(np.int64)
        flat = np.zeros(n * cfg.vocab_size, dtype=np.float32)
        np.add.at(flat, rows * cfg.vocab_size + ids, 1.0)
        bow = flat.reshape(n, cfg.vocab_size)
        norms = np.linalg.norm(bow, axis=1, keepdims=True)
        bow = np.where(norms > 0, bow / np.maximum(norms, 1e-9), bow)

        # float32 weights: np.sqrt(python float) is a float64 scalar, which
        # under NumPy-2 promotion silently upcast the whole index to float64
        # (2x arena bytes for noise-level precision the scores never used).
        w = np.float32(self.learned_weight)
        return np.concatenate([learned * np.sqrt(w),
                               bow * np.sqrt(np.float32(1.0) - w)], axis=1)

    def _embed_query(self, query: str) -> np.ndarray:
        with self._lock:
            cached = self._query_cache.get(query)
            if cached is not None:
                self._query_cache.move_to_end(query)
                self.query_cache_hits += 1
                return cached
            self.query_cache_misses += 1
        vec = self._embed([query])[0]  # slow: outside the lock
        with self._lock:
            self._query_cache[query] = vec
            while len(self._query_cache) > self._query_cache_size:
                self._query_cache.popitem(last=False)
        return vec

    # ── arena index ──────────────────────────────────────────────────

    def _reserve(self, extra: int, dim: int) -> None:
        need = self._size + extra
        if self._arena is None:
            self._arena = np.zeros((max(pow2_bucket(max(need, 1)), 64), dim),
                                   dtype=np.float32)
            return
        if need <= len(self._arena):
            return
        cap = len(self._arena)
        while cap < need:
            cap *= 2
        grown = np.zeros((cap, dim), dtype=np.float32)
        grown[:self._size] = self._arena[:self._size]
        self._arena = grown

    def sync(self, facts: list) -> int:
        if not facts:
            return 0
        with self.timer.stage("sync"):
            docs = [fact_document(f) for f in facts]
            vectors = self._embed(docs)  # slow: outside the lock
            with self._lock:
                # Reserve rows only for ids not already resident: a full-store
                # re-sync consumes zero new rows and must not trigger a
                # capacity doubling.
                fresh = sum(1 for f in facts if f.id not in self._pos)
                self._reserve(fresh, vectors.shape[1])
                for fact, doc, vec in zip(facts, docs, vectors):
                    self._docs[fact.id] = doc
                    row = self._pos.get(fact.id)
                    if row is not None:  # re-sync: overwrite in place
                        self._arena[row] = vec
                        continue
                    self._arena[self._size] = vec
                    self._pos[fact.id] = self._size
                    self._ids.append(fact.id)
                    self._size += 1
                self._arena_dirty = True
        return len(facts)

    def _scores(self, q: np.ndarray, size: int) -> np.ndarray:
        """Scores for the live arena rows — callers hold ``self._lock``.
        Single-device: the numpy BLAS matmul (the oracle). Mesh: rows
        sharded over dp through the compiled plan variant; the committed
        device copy survives across queries and re-commits (attributed as
        the ``shard`` stage) only after host mutations."""
        if self._mesh is None:
            return self._arena[:size] @ q
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel import plan as sharding_plan

        rows = sharding_plan.serve_bucket(size, self._mesh,
                                          plan=self._plan_family)
        if self._arena_dirty or self._device_arena_rows != rows:
            with self.timer.stage("shard"):
                padded = np.zeros((rows, self._arena.shape[1]), np.float32)
                padded[:size] = self._arena[:size]
                self._device_arena = jax.device_put(
                    padded, NamedSharding(self._mesh, P("dp", None)))
                self._device_arena_rows = rows
                self._arena_dirty = False
        q_dev = jax.device_put(q.astype(np.float32, copy=False),
                               NamedSharding(self._mesh, P()))
        scores = np.asarray(sharding_plan.arena_scores(
            self._device_arena, q_dev, self._mesh))
        return scores[:size]

    def search(self, query: str, k: int = 5) -> list[dict]:
        if self._size == 0:
            return []
        with self.timer.stage("search"):
            q = self._embed_query(query)
            with self._lock:
                size = self._size
                if size == 0:  # raced with a remove draining the arena
                    return []
                scores = self._scores(q, size)
                if 0 < k < size:
                    # argpartition gives the kth-largest score in O(n); keep
                    # every index at or above it so boundary ties are broken
                    # by the same deterministic (-score, id) order as a full
                    # sort would.
                    kth = scores[np.argpartition(-scores, k - 1)[:k]].min()
                    cand = np.nonzero(scores >= kth)[0]
                else:
                    cand = np.arange(size)
                order = sorted(cand, key=lambda i: (-scores[i], self._ids[i]))[:k]
                return [{"id": self._ids[i],
                         "document": self._docs.get(self._ids[i], ""),
                         "score": float(scores[i])} for i in order]

    def remove(self, ids) -> int:
        """Drop pruned facts from the index so search never returns them.
        Ids already absent count as settled (the desired state holds).
        Compaction is tombstone-free: the last live row swaps into the hole."""
        dead = set(ids)
        if not dead:
            return 0
        with self._lock:
            for fid in dead:
                self._docs.pop(fid, None)
                row = self._pos.pop(fid, None)
                if row is None:
                    continue
                last = self._size - 1
                if row != last:
                    self._arena[row] = self._arena[last]
                    moved = self._ids[last]
                    self._ids[row] = moved
                    self._pos[moved] = row
                self._ids.pop()
                self._size -= 1
            self._arena_dirty = True
        return len(dead)

    def count(self) -> int:
        return self._size


def create_embeddings(config: dict, logger, http_post: Callable = _default_http_post,
                      timer: Optional[StageTimer] = None):
    backend = (config or {}).get("backend", "local")
    if backend == "chroma":
        return ChromaEmbeddings(config, logger, http_post)
    if backend == "local":
        mesh = None
        plan_family = (config or {}).get("planFamily", "embeddings_forward")
        if (config or {}).get("meshServing"):
            # Opt-in (like serve.meshServing): builds the mesh NOW — a
            # deliberate eager jax touch, because a serving config that
            # cannot get its devices must fail at construction, not on
            # the first sync. meshShape null = every local device. The
            # default embeddings plan is dp-only, so under the default
            # axes a multi-dim shape (the serve config's [2, 4] form,
            # which the schema accepts) flattens to its device count
            # instead of crashing Mesh construction. ``meshAxes``
            # (ISSUE 18) opts into multi-axis families — the
            # expert-parallel plan wants ("dp", "ep"); a shape of the
            # wrong rank then auto-factors over the first two axes.
            import math

            import jax

            from ..parallel.mesh import _factor, cached_mesh

            axes = tuple((config or {}).get("meshAxes") or ("dp",))
            shape = (config or {}).get("meshShape") or (len(jax.devices()),)
            shape = tuple(int(s) for s in shape)
            n = math.prod(shape)
            if len(axes) == 1:
                mesh = cached_mesh((n,), axes)
            else:
                if len(shape) != len(axes):
                    shape = _factor(n) + (1,) * (len(axes) - 2)
                mesh = cached_mesh(shape, axes)
        return LocalEmbeddings(logger,
                               checkpoint_dir=(config or {}).get("checkpointDir"),
                               timer=timer, mesh=mesh,
                               plan_family=plan_family)
    return None

"""Maintenance: relevance decay + embeddings sync on interval timers
(reference: knowledge-engine/src/maintenance.ts:32-90 — unref'd timers; here
daemon threads, or manual ``run_*`` ticks when wall timers are off)."""

from __future__ import annotations

import threading
from typing import Optional

from ..utils.stage_timer import StageTimer


class Maintenance:
    def __init__(self, fact_store, embeddings, logger,
                 decay_hours: float = 24.0, sync_minutes: float = 30.0,
                 wall_timers: bool = True, timer: Optional[StageTimer] = None,
                 lifecycle=None):
        self.fact_store = fact_store
        self.embeddings = embeddings
        self.logger = logger
        self.decay_hours = decay_hours
        self.sync_minutes = sync_minutes
        self.wall_timers = wall_timers
        self.timer = timer if timer is not None else StageTimer()
        # Workspace lifecycle (ISSUE 11): idle hibernation needs a periodic
        # probe precisely because an idle store gets no traffic to piggyback
        # on — the maintenance loop is that probe.
        self.lifecycle = lifecycle
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._synced_ids: set = set()

    def run_decay(self) -> int:
        with self.timer.stage("decay"):
            pruned = self.fact_store.decay_facts()
        if pruned:
            self.logger.info(f"decay pruned {pruned} stale facts")
        return pruned

    def run_embeddings_sync(self) -> int:
        if self.embeddings is None or not self.embeddings.enabled():
            return 0
        # Reconcile prunes first (decay / maxFacts cap) so the index never
        # keeps serving facts the store has deleted. Snapshot under the
        # store lock: the gateway thread ingests concurrently, and iterating
        # the live dict would die mid-sync on a resize.
        facts_now = self.fact_store.snapshot()
        current = {f.id for f in facts_now}
        dead = self._synced_ids - current
        failed_dead: set = set()
        if dead:
            if hasattr(self.embeddings, "remove"):
                # remove() returns how many ids are settled (deleted or
                # permanently undeletable). A transient failure settles fewer:
                # keep those ids marked as synced so the next tick retries.
                if self.embeddings.remove(dead) < len(dead):
                    failed_dead = dead
            else:
                self.logger.warn(f"{len(dead)} pruned facts remain in the "
                                 "embeddings backend (no remove support)")
        self._synced_ids = (self._synced_ids & current) | failed_dead
        pending = [f for f in facts_now if f.id not in self._synced_ids]
        if not pending:
            return 0
        n = self.embeddings.sync(pending)
        if n:
            self._synced_ids.update(f.id for f in pending)
        return n

    def run_hibernation(self) -> int:
        """One idle-eviction tick (ISSUE 11): hibernate every workspace the
        lifecycle manager reports past its idle horizon. Returns evictions."""
        if self.lifecycle is None:
            return 0
        n = 0
        for ws in self.lifecycle.idle_victims():
            if self.lifecycle.hibernate(ws):
                n += 1
        return n

    def _loop(self, interval_s: float, fn) -> None:
        while not self._stop.wait(interval_s):
            try:
                fn()
            except Exception as exc:  # noqa: BLE001
                self.logger.error(f"maintenance tick failed: {exc}")

    def start(self) -> None:
        if not self.wall_timers:
            return
        jobs = [(self.decay_hours * 3600, self.run_decay, "ke-decay"),
                (self.sync_minutes * 60, self.run_embeddings_sync,
                 "ke-embeddings")]
        if self.lifecycle is not None and self.lifecycle.idle_s > 0:
            # Probe at half the idle horizon: an idle store sleeps at most
            # 1.5× idleSeconds past its last message.
            jobs.append((self.lifecycle.idle_s / 2, self.run_hibernation,
                         "ke-hibernate"))
        for interval, fn, name in jobs:
            t = threading.Thread(target=self._loop, args=(interval, fn),
                                 daemon=True, name=name)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

"""Knowledge engine plugin (reference: knowledge-engine/index.ts:7-39,
src/hooks.ts:19-124).

Hook layout: session_start @200 loads the store + starts maintenance;
message_received/message_sent @100 extract entities→facts (+ optional LLM
batch); gateway_stop @900 flushes and stops timers.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..config.loader import load_plugin_config
from ..config.manifest import PluginManifest, enabled_section
from ..core.api import PluginCommand, PluginService
from ..resilience.faults import maybe_fail
from ..storage.journal import get_journal, journal_settings
from ..storage.lifecycle import LifecycleManager, lifecycle_settings
from ..utils.stage_timer import StageTimer
from .embeddings import create_embeddings
from .entity_extractor import EntityExtractor
from .fact_store import FactStore
from .llm_enhancer import KnowledgeLlmEnhancer
from .maintenance import Maintenance

DEFAULTS = {
    "enabled": True,
    "workspace": None,
    # storage.journal (ISSUE 7): debounced facts.json saves ride the shared
    # group-commit workspace journal; false restores the atomic-rename path.
    # storage.lifecycle (ISSUE 11): snapshot shipping + tiering on the
    # shared journal; idle hibernation of the fact store (idleSeconds > 0).
    "storage": {"maxFacts": 2000, "writeDebounceMs": 2000, "journal": True,
                "lifecycle": True},
    "extraction": {"minImportance": 0.5, "mentionPredicate": "mentioned"},
    "llm": {"enabled": False, "batchSize": 3},
    "embeddings": {"backend": "local", "enabled": True,
                   "endpoint": "http://localhost:8000/api/v2/collections/{name}/upsert",
                   "collectionName": "openclaw-facts",
                   # ISSUE 15: data-parallel mesh for local embeddings —
                   # batched _embed + arena search shard over dp
                   # (parallel/plan.py "embeddings_forward" plan).
                   # Default off: the single-device path is the oracle.
                   "meshServing": False, "meshShape": None},
    "maintenance": {"decayHours": 24, "syncMinutes": 30},
}

MANIFEST = PluginManifest(
    id="knowledge-engine",
    description="Entity extraction into a decaying fact store with embeddings",
    config_schema={
        "type": "object",
        "properties": {
            "enabled": {"type": "boolean"},
            "workspace": {"type": ["string", "null"]},
            "storage": {"type": "object", "properties": {
                "maxFacts": {"type": "integer", "minimum": 1},
                "writeDebounceMs": {"type": "integer", "minimum": 0},
                "journal": {"type": ["boolean", "object"]},
                "lifecycle": {"type": ["boolean", "object"]}}},
            "extraction": {"type": "object", "properties": {
                "minImportance": {"type": "number", "minimum": 0, "maximum": 1},
                "mentionPredicate": {"type": "string"}}},
            "llm": enabled_section(batchSize={"type": "integer", "minimum": 1}),
            "embeddings": enabled_section(
                backend={"type": "string", "enum": ["local", "chroma", "none"]},
                endpoint={"type": "string"},
                collectionName={"type": "string"},
                meshServing={"type": "boolean"},
                meshShape={"type": ["array", "null"]}),
            "maintenance": {"type": "object", "properties": {
                "decayHours": {"type": "number", "minimum": 0},
                "syncMinutes": {"type": "number", "minimum": 0}}},
        },
    },
    hooks=("session_start", "message_received", "message_sent", "gateway_stop"),
)


class KnowledgeEnginePlugin:
    id = "knowledge-engine"
    manifest = MANIFEST

    def __init__(self, workspace: Optional[str] = None,
                 clock: Callable[[], float] = time.time,
                 call_llm=None, wall_timers: bool = True, http_post=None):
        self._workspace_override = workspace
        self.clock = clock
        self.call_llm = call_llm
        self.wall_timers = wall_timers
        self.http_post = http_post
        self.config: dict = {}
        # One shared StageTimer across store / embeddings / maintenance: the
        # serve-path breakdown (ingest, query, sync, search, decay, extract)
        # reads as one attribution surface (ISSUE 2, mirroring the trace
        # analyzer's stageMs).
        self.timer = StageTimer()
        self.extractor: Optional[EntityExtractor] = None
        self.fact_store: Optional[FactStore] = None
        self.embeddings = None
        self.maintenance: Optional[Maintenance] = None
        self.enhancer: Optional[KnowledgeLlmEnhancer] = None
        self.lifecycle: Optional[LifecycleManager] = None
        self._ws_key = ""
        self._maintenance_started = False

    def register(self, api) -> None:
        self.config = load_plugin_config(self.id, api.plugin_config,
                                         defaults=DEFAULTS, logger=api.logger)
        if not self.config.get("enabled", True):
            api.logger.info("disabled via config")
            return
        self.logger = api.logger
        workspace = (self._workspace_override or self.config.get("workspace")
                     or api.config.get("workspace") or ".")
        self.extractor = EntityExtractor(api.logger, clock=self.clock)
        # Workspace lifecycle (ISSUE 11): shipping/tiering settings ride the
        # shared journal (first creator wins); the manager drives idle
        # hibernation of the fact store through the maintenance loop.
        ls = lifecycle_settings(self.config)
        self._ws_key = str(workspace)
        if ls["enabled"]:
            self.lifecycle = LifecycleManager(ls, clock=self.clock,
                                              logger=api.logger)
            if hasattr(api, "register_lifecycle"):
                api.register_lifecycle("knowledge", self.lifecycle)
        else:
            self.lifecycle = None
        # Shared per-workspace group-commit journal (ISSUE 7); falls back to
        # the legacy debounced atomic write when disabled or unopenable.
        js = journal_settings(self.config)
        self.journal = (get_journal(workspace, js, clock=self.clock,
                                    wall=self.wall_timers, logger=api.logger,
                                    lifecycle=ls if ls["enabled"] else None,
                                    lifecycle_timer=(
                                        self.lifecycle.timer_for(self._ws_key)
                                        if self.lifecycle is not None
                                        else None))
                        if js["enabled"] else None)
        if self.journal is not None and hasattr(api, "register_journal"):
            api.register_journal(f"journal:{workspace}", self.journal)
        self.fact_store = FactStore(workspace, self.config.get("storage"),
                                    api.logger, clock=self.clock,
                                    wall_timers=self.wall_timers,
                                    timer=self.timer, journal=self.journal)
        if self.lifecycle is not None:
            # The store hibernates to its journaled snapshot; the shared
            # journal itself stays open — cortex (or gateway stop) owns
            # closing it, and knowledge's eviction is about the facts dict
            # and its indexes, not the wal fd.
            self.lifecycle.register(self._ws_key, self.fact_store.hibernate,
                                    owner="knowledge")
        kwargs = {"http_post": self.http_post} if self.http_post else {}
        self.embeddings = create_embeddings(self.config.get("embeddings"),
                                            api.logger, timer=self.timer,
                                            **kwargs)
        mcfg = self.config.get("maintenance", {})
        self.maintenance = Maintenance(self.fact_store, self.embeddings, api.logger,
                                       decay_hours=mcfg.get("decayHours", 24),
                                       sync_minutes=mcfg.get("syncMinutes", 30),
                                       wall_timers=self.wall_timers,
                                       timer=self.timer,
                                       lifecycle=self.lifecycle)
        if self.config.get("llm", {}).get("enabled") and self.call_llm is not None:
            self.enhancer = KnowledgeLlmEnhancer(self.call_llm, api.logger,
                                                 self.config["llm"].get("batchSize", 3))

        api.on("session_start", self._on_session_start, priority=200)
        api.on("message_received", self._on_message, priority=100)
        api.on("message_sent", self._on_message, priority=100)
        api.on("gateway_stop", self._on_gateway_stop, priority=900)
        api.register_service(PluginService(
            id="knowledge-engine",
            start=lambda ctx: self._ensure_loaded(),
            stop=lambda ctx: self._shutdown()))
        api.register_stage_timer("knowledge", self.timer)
        api.register_command(PluginCommand(
            name="knowledge", description="Knowledge engine status + search",
            accepts_args=True,
            handler=lambda ctx: {"text": self.status_text(ctx.get("args", ""))}))

    # ── lifecycle ────────────────────────────────────────────────────

    def _ensure_loaded(self) -> None:
        if self.fact_store.loaded:
            return
        # Wake path (ISSUE 11): after a hibernation this re-load IS the
        # recovery — the ``lifecycle.wake`` fault fires before it so a
        # crashed wake leaves the store empty-and-unloaded for the next
        # message to retry (the hook handlers are fail-open).
        waking = (self.lifecycle is not None
                  and self.lifecycle.is_sleeping(self._ws_key))
        t0 = time.perf_counter()
        if waking:
            maybe_fail("lifecycle.wake")
        self.fact_store.load()
        if not self._maintenance_started:
            self.maintenance.start()
            self._maintenance_started = True
        if waking:
            # Hibernation dropped the owner callback (the manager must not
            # pin closures for sleeping workspaces) — re-register on wake.
            self.lifecycle.register(self._ws_key, self.fact_store.hibernate,
                                    owner="knowledge")
            self.lifecycle.note_wake(self._ws_key,
                                     (time.perf_counter() - t0) * 1000.0)

    def _shutdown(self) -> None:
        if self.maintenance is not None:
            self.maintenance.stop()
        if self.enhancer is not None and self.fact_store is not None:
            # Flush a partial LLM batch so short sessions still extract facts.
            for f in self.enhancer.send_batch() or []:
                self.fact_store.add_fact(f["subject"], f["predicate"], f["object"],
                                         source="extracted-llm")
        if self.fact_store is not None:
            self.fact_store.flush()

    # ── hooks ────────────────────────────────────────────────────────

    def _on_session_start(self, event: dict, ctx: dict):
        try:
            self._ensure_loaded()
        except Exception as exc:  # noqa: BLE001
            self.logger.error(f"session_start failed: {exc}")
        return None

    def _on_message(self, event: dict, ctx: dict):
        try:
            content = event.get("content") or ""
            if not content:
                return None
            self._ensure_loaded()
            if self.lifecycle is not None:
                # Recency stamp; idle eviction itself runs on the
                # maintenance probe (an idle store gets no messages).
                self.lifecycle.note_traffic(self._ws_key)
            min_importance = self.config.get("extraction", {}).get("minImportance", 0.5)
            predicate = self.config.get("extraction", {}).get("mentionPredicate", "mentioned")
            with self.timer.stage("extract"):
                entities = self.extractor.extract(content)
            for entity in entities:
                if entity.importance < min_importance:
                    continue
                self.fact_store.add_fact("conversation", predicate, entity.value,
                                         source="extracted-regex")
            if self.enhancer is not None:
                facts = self.enhancer.add_to_batch(content)
                for f in facts or []:
                    self.fact_store.add_fact(f["subject"], f["predicate"], f["object"],
                                             source="extracted-llm")
        except Exception as exc:  # noqa: BLE001
            self.logger.error(f"message extraction failed: {exc}")
        return None

    def _on_gateway_stop(self, event: dict, ctx: dict):
        try:
            self._shutdown()
        except Exception as exc:  # noqa: BLE001
            self.logger.error(f"gateway_stop failed: {exc}")
        return None

    # ── status ───────────────────────────────────────────────────────

    def stats(self) -> dict:
        """Machine-readable serve-path stats: counts plus the shared
        StageTimer breakdown (same shape discipline as the trace analyzer's
        ``runStats.stageMs``) so a slow knowledge path arrives
        pre-attributed to ingest / query / sync / search / decay."""
        self._ensure_loaded()
        # snapshot(): ms/counts/quantiles from one lock round-trip — this
        # timer is shared with the maintenance daemon, so back-to-back
        # stages_ms()+counts() reads could attribute different traffic.
        snap = self.timer.snapshot()
        out = {
            "facts": self.fact_store.count(),
            "embedded": (self.embeddings.count()
                         if hasattr(self.embeddings, "count") else None),
            "stageMs": snap["stages_ms"],
            "stageCounts": snap["counts"],
            "stageQuantiles": snap["quantiles"],
        }
        if hasattr(self.embeddings, "query_cache_hits"):
            out["queryCache"] = {"hits": self.embeddings.query_cache_hits,
                                 "misses": self.embeddings.query_cache_misses}
        return out

    def _stage_line(self) -> str:
        stage_ms = self.timer.stages_ms()
        if not stage_ms:
            return ""
        return "stages: " + " ".join(f"{k}={v:.1f}ms" for k, v in stage_ms.items())

    def status_text(self, args: str = "") -> str:
        self._ensure_loaded()
        query = args.strip()
        if query:
            results = self.fact_store.query(text=query, limit=5)
            lines = [f"📚 facts matching {query!r}:"]
            lines += [f"  {f.subject} {f.predicate} {f.object} "
                      f"(rel={f.relevance:.2f}, {f.source})" for f in results]
            if hasattr(self.embeddings, "search") and self.embeddings.count():
                lines.append("  semantic:")
                lines += [f"    {r['document']} ({r['score']:.2f})"
                          for r in self.embeddings.search(query, k=3)]
            stage = self._stage_line()
            if stage:
                lines.append(f"  {stage}")
            return "\n".join(lines)
        n_vec = self.embeddings.count() if hasattr(self.embeddings, "count") else "n/a"
        base = (f"📚 knowledge: {self.fact_store.count()} facts, "
                f"{n_vec} embedded "
                f"(backend={self.config.get('embeddings', {}).get('backend')})")
        stage = self._stage_line()
        return f"{base}\n  {stage}" if stage else base

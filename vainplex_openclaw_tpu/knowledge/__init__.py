"""Knowledge engine (reference: packages/openclaw-knowledge-engine).

Regex NER over conversation messages → canonical entities; subject-
predicate-object fact store with relevance decay; optional LLM fact
extraction; embeddings sync (ChromaDB-shaped HTTP, plus a local on-device
CortexEncoder index — the TPU-native path); maintenance timers.
"""

from .plugin import KnowledgeEnginePlugin

__all__ = ["KnowledgeEnginePlugin"]
